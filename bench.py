#!/usr/bin/env python
"""Benchmark harness — the replacement for the reference's bench.sh
(/root/reference/bench.sh:18-33, which greps `sec=` out of 3 reporter
runs per workload).

Runs the encoded workloads on the real chip (the environment's default
JAX backend — the axon-tunneled TPU under the driver; CPU elsewhere)
and prints exactly ONE JSON line on stdout:

    {"metric": ..., "value": N, "unit": "states/sec",
     "vs_baseline": N, "detail": {...}}

``value`` is unique-states/sec on the headline workload (largest
encoded state space), timed warm (second run; the XLA compile cache
makes re-runs and CLI invocations warm too). ``vs_baseline`` is the
speedup over the sequential host BFS oracle measured live on this same
machine — the reference publishes no numbers (BASELINE.md) and its
Rust toolchain isn't in this image, so the host oracle is the honest
stand-in for the reference's single-thread CPU search.

Per-workload details go to stderr; ``--verbose`` adds per-run wave
metrics (frontier size, occupancy, dedup ratio — plus shuffle volume
on SHARDED lanes, where the engines count routed rows; the standard
lanes are single-chip and have no shuffle). A lane whose checker
reports ``shuffle_volume`` gets it in its detail row, and a traced
lane whose TRACE carries per-shard ``shard_wave`` events additionally
gets the derived ``shard_balance`` skew/routing summary
(telemetry.shard_balance — the same block the MULTICHIP dryrun
embeds), so direction-1 mesh runs land with skew numbers attached.
Every lane also embeds its ``memory_plan`` totals (the resident-buffer
ledger, stateright_tpu/memplan.py) and — on traced lanes, where the
watermark polls — the run's device peak bytes, so BENCH artifacts
land with memory numbers attached the way they land with balance
numbers. Round 14 adds the LATENCY axis: every device lane embeds its
host-side dispatch/sync-floor wall split (``latency_accounting`` —
kept even untraced) and the per-lane compile-cache ledger delta
(XLA compile-or-fetch count, persistent-cache disk hits, total cold
wall — ``checkers.tpu.compile_ledger_totals``), and the provenance
block carries the process totals, so BENCH_r06's warm/cold A/B is
attributable from the artifact alone.
"""

import argparse
import json
import sys
import time


def _stderr(*args):
    print(*args, file=sys.stderr, flush=True)


def time_checker(spawn, runs=2):
    """Spawn+join ``runs`` times; return (checker, best_seconds).

    The first run pays any residual compile cost (the persistent XLA
    cache usually absorbs it); the best run is reported, mirroring
    bench.sh's min-of-3 convention.
    """
    best = float("inf")
    checker = None
    for _ in range(runs):
        c = spawn()
        t0 = time.monotonic()
        c.join()
        dt = time.monotonic() - t0
        best = min(best, dt)
        checker = c
    return checker, best


def bench_host_oracle():
    """Sequential host BFS on 2pc rm=5 — the vs_baseline denominator.

    Caveat (VERDICT r4): this is a ONE-thread Python oracle (~2.3k
    st/s). The reference's Rust BFS on a many-core host would be
    orders of magnitude faster, so ``vs_baseline`` measures the gap to
    THIS repo's host engine, not to the reference binary (which isn't
    in the image; the reference also publishes no numbers,
    BASELINE.md). ``threads(n)`` exists and is real, but CPython's GIL
    makes pure-Python model callbacks serialize, so n>1 does not make
    this denominator honestly faster."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    c = TwoPhaseSys(rm_count=5).checker().spawn_bfs()
    t0 = time.monotonic()
    c.join()
    dt = time.monotonic() - t0
    sps = c.unique_state_count() / dt
    _stderr(
        f"host-oracle  2pc rm=5: unique={c.unique_state_count()} "
        f"sec={dt:.2f} states/sec={sps:,.0f}"
    )
    return sps


def tpu_workloads(quick=False):
    """(name, spawn, hybrid_spawn, expected_unique) for every encoded
    workload; the LAST entry is the headline. ``hybrid_spawn`` is set
    for every sub-100k lane (VERDICT r5 item 7): those lanes complete
    in ~one axon RTT on the wave engine, so their states/sec measures
    the LINK — the hybrid racer's wall time is the product answer and
    is recorded alongside.

    Per-wave BUDGETS are auto-sized (``cand_capacity="auto"``: start
    from the persisted store or a growth heuristic, resize loudly from
    the measured peak on overflow — VERDICT r5 item 6 retired
    ``TUNED_ENGINE_CAPS`` and the per-lane caps tables). Only
    STRUCTURAL sizes remain per lane: ``capacity`` from the pinned
    state count, ``frontier_capacity`` from the measured wave peak.
    """
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    def twopc(rm, hybrid=False, **kw):
        def spawn():
            b = TwoPhaseSys(rm_count=rm).checker()
            fn = b.spawn_hybrid if hybrid else b.spawn_tpu_sortmerge
            return fn(track_paths=False, cand_capacity="auto", **kw)

        return spawn

    def twopc_sym(rm, **kw):
        # The device symmetry-reduction lane (ROADMAP 4(a)): the
        # same protocol with candidates canonicalized before dedup
        # (ops/canonical.py), so the engine explores the orbit
        # quotient — 8,832 -> 314 at rm=5. Counts are the PERFECT
        # canonicalizer's, order-independent and host-oracle-pinned
        # (tests/test_device_symmetry.py; the reference's 665 is a
        # DFS-order artifact, see symmetry.py).
        def spawn():
            return (
                TwoPhaseSys(rm_count=rm)
                .checker()
                .symmetry()
                .spawn_tpu_sortmerge(
                    track_paths=False, cand_capacity="auto", **kw
                )
            )

        return spawn

    from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model
    from stateright_tpu.models.paxos_tpu import STRUCTURAL_SIZES

    def paxos(clients, hybrid=False, **kw):
        def spawn():
            b = paxos_model(
                PaxosModelCfg(client_count=clients, server_count=3)
            ).checker()
            fn = b.spawn_hybrid if hybrid else b.spawn_tpu_sortmerge
            return fn(track_paths=False, cand_capacity="auto",
                      **dict(STRUCTURAL_SIZES[clients], **kw))

        return spawn

    # The literal driver configs (BASELINE.md:29-32) come first: tiny
    # spaces that measure the dispatch/sync floor more than compute
    # (the reference does these in ms on the host; the hybrid racer is
    # the right engine for them — these lanes keep the TPU engine
    # honest on breadth, not just the big-space headline).
    from stateright_tpu.models.increment import IncrementLock
    from stateright_tpu.models.single_copy_register import (
        SingleCopyRegisterCfg,
        single_copy_register_model,
    )

    def increment_lock(n, hybrid=False, **kw):
        def spawn():
            b = IncrementLock(thread_count=n).checker()
            fn = b.spawn_hybrid if hybrid else b.spawn_tpu_sortmerge
            return fn(track_paths=False, cand_capacity="auto", **kw)

        return spawn

    def single_copy(n, hybrid=False, **kw):
        def spawn():
            b = single_copy_register_model(
                SingleCopyRegisterCfg(client_count=n)
            ).checker()
            fn = b.spawn_hybrid if hybrid else b.spawn_tpu_sortmerge
            # Dense dispatch: the SPARSE chunk program for this
            # compiled encoding reliably gets the axon remote
            # compile helper SIGKILLed (round 5; the dense program
            # compiles and runs fine, and at K=21 the dense wave
            # is cheap anyway).
            return fn(track_paths=False, sparse=False,
                      cand_capacity="auto", **kw)

        return spawn

    # The COMPILED lanes (ROADMAP direction 5: the compiled encodings
    # AT PRODUCTION SHAPES, beside their hand-encoding denominators):
    # the count-comparable 2pc system actor model and actor paxos
    # through the generic actor->encoding compiler — zero hand device
    # code — at the SAME pinned counts as the hand lanes (8,832 /
    # 50,816 / 296,448 / 16,668), so the compiled-vs-hand gap is a
    # parity RATIO of like-for-like walls (COMPILED_PARITY below),
    # not a comparison across different state spaces. Encodings are
    # built ONCE and memoized outside the spawn closure: the compile
    # (and, for paxos, the reachable-mode host harvest) is a
    # one-time cost the timed A/B must not re-pay per pooled run.
    _compiled_enc_cache = {}

    def twopc_sys_compiled(rm, **kw):
        from stateright_tpu.models.two_phase_commit_actors import (
            two_phase_sys_actor_model,
            two_phase_sys_compiled_encoded,
        )

        def spawn():
            key = ("2pc-sys", rm)
            if key not in _compiled_enc_cache:
                _compiled_enc_cache[key] = (
                    two_phase_sys_compiled_encoded(rm)
                )
            return (
                two_phase_sys_actor_model(rm)
                .checker()
                .spawn_tpu_sortmerge(
                    encoded=_compiled_enc_cache[key],
                    track_paths=False, cand_capacity="auto", **kw,
                )
            )

        return spawn

    def paxos_compiled(clients, servers, **kw):
        def spawn():
            from stateright_tpu.models.paxos import (
                paxos_compiled_encoded,
            )

            cfg = PaxosModelCfg(
                client_count=clients, server_count=servers,
                put_count=1,
            )
            key = ("paxos", clients, servers)
            if key not in _compiled_enc_cache:
                _compiled_enc_cache[key] = paxos_compiled_encoded(cfg)
            return paxos_model(cfg).checker().spawn_tpu_sortmerge(
                encoded=_compiled_enc_cache[key], track_paths=False,
                cand_capacity="auto", **kw,
            )

        return spawn

    loads = [
        (
            # Driver config `2pc check 3` (examples/2pc.rs:153-154).
            "2pc rm=3",
            twopc(3, capacity=1 << 10, frontier_capacity=1 << 8),
            twopc(3, hybrid=True, capacity=1 << 10,
                  frontier_capacity=1 << 8),
            288,
        ),
        (
            # Driver config `increment_lock` (examples/increment_lock.rs
            # CLI default: 3 threads).
            "increment_lock n=3",
            increment_lock(3, capacity=1 << 10,
                           frontier_capacity=1 << 8),
            increment_lock(3, hybrid=True, capacity=1 << 10,
                           frontier_capacity=1 << 8),
            61,
        ),
        (
            # Driver config `single-copy-register check 3`
            # (examples/single-copy-register.rs; count host-pinned).
            "single-copy 3c",
            single_copy(3, capacity=1 << 13,
                        frontier_capacity=1 << 11),
            single_copy(3, hybrid=True, capacity=1 << 13,
                        frontier_capacity=1 << 11),
            4243,
        ),
        (
            "2pc rm=5",
            twopc(5, capacity=1 << 14, frontier_capacity=1 << 11),
            twopc(5, hybrid=True, capacity=1 << 14,
                  frontier_capacity=1 << 11),
            8832,
        ),
        (
            # The compiled 2pc lane AT the hand lane's shape (ISSUE
            # 20): the count-comparable system actor model
            # (two_phase_sys_actor_model — host-parity pinned at the
            # TwoPhaseSys counts) through the codegen-optimized
            # compiler. The "2pc rm=5" lane above is the parity
            # denominator (COMPILED_PARITY).
            "2pc-actors rm=5 (compiled)",
            twopc_sys_compiled(5, capacity=1 << 14,
                               frontier_capacity=1 << 11),
            None,
            8832,
        ),
        (
            # the rm=5..7 symmetry sweep rides beside its raw lanes:
            # same protocol, canonical-fingerprint dedup, the lane
            # detail records the reduction ratio (SYM_LANES below)
            "2pc rm=5 (sym)",
            twopc_sym(5, capacity=1 << 11, frontier_capacity=256),
            None,
            314,
        ),
        (
            "paxos 2c/3s",
            paxos(2),
            paxos(2, hybrid=True),
            16668,
        ),
        (
            # The compiled paxos lane at the hand "paxos 2c/3s"
            # shape (same PaxosModelCfg; reachable-mode harvest is
            # paid once at encoding build, outside the timed runs).
            "paxos 2c/3s (compiled)",
            paxos_compiled(2, 3, capacity=1 << 15,
                           frontier_capacity=1 << 12),
            None,
            16668,
        ),
        (
            "2pc rm=6",
            twopc(6, capacity=1 << 16, frontier_capacity=1 << 14),
            twopc(6, hybrid=True, capacity=1 << 16,
                  frontier_capacity=1 << 14),
            50816,
        ),
        (
            "2pc-actors rm=6 (compiled)",
            twopc_sys_compiled(6, capacity=1 << 16,
                               frontier_capacity=1 << 14),
            None,
            50816,
        ),
        (
            "2pc rm=6 (sym)",
            twopc_sym(6, capacity=1 << 12, frontier_capacity=512),
            None,
            553,
        ),
        (
            "2pc rm=7 (sym)",
            twopc_sym(7, capacity=1 << 13, frontier_capacity=1024),
            None,
            920,
        ),
        (
            # before the hand rm=7 so THAT lane stays the traced
            # headline (the compiled lane is a parity lane, not a
            # throughput headline)
            "2pc-actors rm=7 (compiled)",
            twopc_sys_compiled(7, capacity=1 << 19,
                               frontier_capacity=1 << 16),
            None,
            296448,
        ),
        (
            # stays LAST among the quick lanes: the raw rm=7 is the
            # --quick headline (the sym lanes are reduction lanes,
            # not throughput headlines)
            "2pc rm=7",
            twopc(7, capacity=1 << 19, frontier_capacity=1 << 16),
            None,
            296448,
        ),
    ]
    # Driver config family `linearizable-register check N ordered`
    # (BASELINE.md:32, bench.sh:33): ABD over FIFO channels, compiled
    # by the actor→encoding compiler in overapprox mode from DECLARED
    # queue bounds (abd_queue_bounds — no host exploration), budgets
    # AUTO-SIZED from measured peaks (no caps table). The 1,212,979
    # count is device-derived, pinned by the depth-prefix host
    # differential in tests/test_actor_compile.py and reproduced
    # across runs; the 4-client driver config's closure is the
    # round-5 frontier (see linearizable_register.py max_domain).
    from stateright_tpu.actor.network import Network
    from stateright_tpu.models.linearizable_register import (
        AbdModelCfg,
        abd_model,
    )

    def abd_ordered(n, **kw):
        def spawn():
            return (
                abd_model(
                    AbdModelCfg(client_count=n, server_count=3),
                    Network.new_ordered(),
                )
                .checker()
                .spawn_tpu_sortmerge(track_paths=False, **kw)
            )

        return spawn

    if not quick:
        loads.append(
            (
                "abd 2c/3s ordered",
                abd_ordered(
                    2,
                    capacity=1 << 21,
                    frontier_capacity=1 << 18,
                    cand_capacity="auto",
                ),
                None,
                1212979,
            )
        )
        loads.append(
            (
                # The north-star workload family (examples/paxos.rs
                # check N): the generalized encoding runs check 3
                # exhaustively on chip. Count verified by host-BFS
                # differential at depths 6-12 (tests/test_paxos_tpu.py)
                # plus the STPU_EXHAUSTIVE host-DFS pin. Sparse action
                # dispatch (round 4): candidate budgets track ENABLED
                # (row, slot) pairs, not F*K slot cells.
                "paxos 3c/3s",
                paxos(3),
                None,
                1194428,
            )
        )
        loads.append(
            (
                "2pc rm=8",
                twopc(8, capacity=1 << 21,
                      frontier_capacity=1 << 19),
                None,
                1745408,
            )
        )
        loads.append(
            (
                # 10.34M states (~the 10^7 regime the north star lives
                # in). The count is reproduced by two independently
                # shaped engine configs (different class ladders, tile
                # counts, and merge programs) and extends the pinned
                # 2pc growth sequence smoothly (ratio 5.925 after
                # 5.754/5.833/5.888); the hash-table engine OOMs the
                # worker at this scale.
                "2pc rm=9",
                twopc(
                    9,
                    capacity=11 << 20,
                    frontier_capacity=3 << 19,
                    # Finer compaction tiles measured ~5% faster at this
                    # scale (lax.sort is superlinear; PERF.md).
                    tile_rows=1 << 20,
                ),
                None,
                10340352,
            )
        )
        loads.append(
            (
                # `paxos check 5`: five clients span two client lanes
                # (VERDICT r3 #6); sized by the padded-HBM rule
                # (PERF.md: a [N, W] state buffer costs ~512 B/row).
                "paxos 5c/3s",
                paxos(5),
                None,
                4711569,
            )
        )
        loads.append(
            (
                # THE north-star workload (BASELINE.md:27 defines the
                # target on `paxos check 4`, examples/paxos.rs:352-465).
                # The true space is 2,372,188 states at depth 28 — far
                # below the pre-measurement ~85M estimate, because the
                # 4th client shares leader 0, whose single-Put guard
                # (proposal-None) caps the ballot blowup. First
                # executed round 4, via sparse dispatch.
                "paxos 4c/3s",
                paxos(4),
                None,
                2372188,
            )
        )
    return loads


#: the symmetry sweep's raw-space denominators (ROADMAP 4(a)): lane
#: name -> (raw unique states, rm). The raw counts are the pinned
#: unreduced 2pc spaces (the non-sym lanes above them); the ratio the
#: lane detail records is raw / canonical.
SYM_LANES = {
    "2pc rm=5 (sym)": (8832, 5),
    "2pc rm=6 (sym)": (50816, 6),
    "2pc rm=7 (sym)": (296448, 7),
}

#: compiled lane -> its hand-encoding denominator lane (round 23):
#: both lanes explore the SAME pinned state space, so
#: parity_ratio = compiled pooled-min wall / hand pooled-min wall is
#: a like-for-like number. Embedded in every compiled lane's detail
#: (and the provenance block) by the post-loop pass in main() — the
#: gap ROADMAP direction 5 chases is a tracked metric from this
#: round on.
COMPILED_PARITY = {
    "2pc-actors rm=5 (compiled)": "2pc rm=5",
    "2pc-actors rm=6 (compiled)": "2pc rm=6",
    "2pc-actors rm=7 (compiled)": "2pc rm=7",
    "paxos 2c/3s (compiled)": "paxos 2c/3s",
}


def bench_sym_host_oracle(rm):
    """The host DFS symmetry oracle (the perfect canonicalizer,
    representative_full) — the device-vs-host-DFS comparison the
    "2pc rm=5 (sym)" lane records: same reduced count, host wall for
    the A/B."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    c = (
        TwoPhaseSys(rm_count=rm)
        .checker()
        .symmetry_fn(lambda s: s.representative_full())
        .spawn_dfs()
    )
    t0 = time.monotonic()
    c.join()
    dt = time.monotonic() - t0
    _stderr(
        f"host-dfs-sym 2pc rm={rm}: unique={c.unique_state_count()} "
        f"sec={dt:.2f}"
    )
    return c.unique_state_count(), dt


def bench_ttfc(runs=2):
    """Time-to-first-counterexample (BASELINE.md primary metric #2):
    wall-clock from spawn to discovery, host DFS vs the TPU engine.
    The increment lanes are true TTFC (their only property is violated,
    so both engines early-exit at the discovery; the wave engine stops
    at the end of the discovering wave). The paxos lane is labeled
    "full check": its always-property holds, so neither engine can
    early-exit — the time measured is verification to completion
    INCLUDING the deep sometimes-discovery."""
    from stateright_tpu.models.increment import Increment

    def host_increment(n):
        def spawn():
            return Increment(thread_count=n).checker().spawn_dfs()

        return spawn

    def tpu_increment(n):
        def spawn():
            return Increment(thread_count=n).checker().spawn_tpu_sortmerge(
                capacity=1 << 16,
                frontier_capacity=1 << 12,
                cand_capacity=1 << 14,
                track_paths=False,
            )

        return spawn

    from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model

    def host_paxos():
        return (
            paxos_model(PaxosModelCfg(client_count=2, server_count=3))
            .checker()
            .spawn_dfs()
        )

    def tpu_paxos():
        return (
            paxos_model(PaxosModelCfg(client_count=2, server_count=3))
            .checker()
            .spawn_tpu_sortmerge(
                capacity=1 << 15,
                frontier_capacity=1 << 12,
                cand_capacity=1 << 14,
                track_paths=False,
            )
        )

    def hybrid_increment(n):
        def spawn():
            return Increment(thread_count=n).checker().spawn_hybrid(
                capacity=1 << 16,
                frontier_capacity=1 << 12,
                cand_capacity=1 << 14,
                track_paths=False,
            )

        return spawn

    def hybrid_paxos():
        return (
            paxos_model(PaxosModelCfg(client_count=2, server_count=3))
            .checker()
            .spawn_hybrid(
                capacity=1 << 15,
                frontier_capacity=1 << 12,
                cand_capacity=1 << 14,
                track_paths=False,
            )
        )

    out = {}
    for name, host_spawn, tpu_spawn, hy_spawn, prop in [
        # Lost-update race: the racy counter violates "fin"
        # (examples/increment.rs semantics) a few steps in — host DFS
        # wins shallow bugs; the wave engine pays per-wave dispatch;
        # the HYBRID racer (spawn_hybrid) adopts whichever engine
        # finishes first, so it is host-or-tie here and device-or-tie
        # on the deep lane (VERDICT r3 weak #5).
        ("increment n=4", host_increment(4), tpu_increment(4),
         hybrid_increment(4), "fin"),
        ("increment n=6", host_increment(6), tpu_increment(6),
         hybrid_increment(6), "fin"),
        # Deep discovery + exhaustion: the chosen value needs a full
        # quorum round (examples/paxos.rs "value chosen") and the
        # holding always-property forces both engines to completion.
        ("paxos 2c/3s full check", host_paxos, tpu_paxos, hybrid_paxos,
         "value chosen"),
    ]:
        h, h_sec = time_checker(host_spawn, runs=runs)
        t, t_sec = time_checker(tpu_spawn, runs=runs)
        # Pair the reported winner with the run that produced the
        # reported (best) time.
        y = None
        y_sec = float("inf")
        y_winner = None
        for _ in range(runs):
            c = hy_spawn()
            t0 = time.monotonic()
            c.join()
            dt = time.monotonic() - t0
            if dt < y_sec:
                y_sec, y_winner = dt, c.winner
            y = c
        assert prop in h.discoveries(), (name, "host")
        assert prop in t.discovered_property_names(), (name, "tpu")
        assert prop in y.discovered_property_names(), (name, "hybrid")
        out[name] = {
            "host_sec": round(h_sec, 4),
            "tpu_sec": round(t_sec, 4),
            "hybrid_sec": round(y_sec, 4),
            "hybrid_winner": y_winner,
            "property": prop,
        }
        kind = (
            "verification to completion incl. the deep discovery"
            if "full check" in name
            else f"first {prop!r} counterexample"
        )
        _stderr(
            f"ttfc {name}: host={h_sec:.3f}s tpu={t_sec:.3f}s "
            f"hybrid={y_sec:.3f}s (winner={y_winner}; {kind})"
        )
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="skip the largest workload")
    ap.add_argument("--verbose", action="store_true", help="per-run wave metrics")
    ap.add_argument("--runs", type=int, default=2)
    ap.add_argument(
        "--trace", nargs="?", const="default",
        choices=("default", "deep"), default=None,
        help="record run telemetry for the HEADLINE workload's timed "
        "runs (stateright_tpu/telemetry.py) and write auto-numbered "
        "TRACE_r*.jsonl + TRACE_r*.trace.json artifacts; 'deep' adds "
        "per-wave syncs (real per-wave walls, so do not read the "
        "headline states/sec off a deep-traced run)",
    )
    args = ap.parse_args()

    import jax

    _stderr(f"backend: {jax.devices()}")

    # The host↔device sync floor: ANY blocking readback costs this
    # much (the axon-tunnel RTT, ~110-130ms; PERF.md §sync-floor).
    # The small driver-config lanes (2pc rm=3/5, increment_lock,
    # single-copy, paxos 2c) complete in ~ONE such round trip warm —
    # their states/sec measures the link, not the engine.
    import jax.numpy as jnp
    import numpy as _np

    _tiny = jax.jit(lambda x: x + 1)
    _np.asarray(_tiny(jnp.uint32(0)))
    _t0 = time.monotonic()
    _np.asarray(_tiny(jnp.uint32(1)))
    sync_floor_ms = round((time.monotonic() - _t0) * 1000, 1)
    _stderr(f"sync floor (blocking readback RTT): {sync_floor_ms} ms")

    host_sps = bench_host_oracle()

    tracer = None
    if args.trace is not None:
        from stateright_tpu.telemetry import RunTracer

        tracer = RunTracer(level=args.trace)

    # LINT cross-reference (round 9): every lane's detail embeds the
    # newest LINT artifact's carry-copy-bytes totals, so a BENCH
    # number and the static switch-carry state it was measured under
    # pair up without hand-matching round numbers (the gated
    # carry-copy rule, stateright_tpu/analysis/).
    from stateright_tpu.artifacts import (
        latest_comms_summary,
        latest_lint_summary,
    )

    lint_ref = latest_lint_summary()
    if lint_ref is not None:
        _stderr(
            f"lint ref: {lint_ref['artifact']} "
            f"carry_copy_bytes={lint_ref['carry_copy_bytes']} "
            f"clean={lint_ref['clean']}"
        )
    # COMM cross-reference (round 13, same best-effort contract):
    # the newest comms-lint artifact — the static collective
    # accounting a traced mesh lane's shard_balance.comms_static
    # reconciles against (PERF.md §comms-lint).
    comms_ref = latest_comms_summary()
    if comms_ref is not None:
        _stderr(
            f"comms ref: {comms_ref['artifact']} "
            f"clean={comms_ref['clean']}"
        )
    # CKPT cross-reference (the durability layer, same best-effort
    # contract): the newest crash-matrix artifact — whether the
    # kill/fault/torn/stale cells all landed on recover-or-refuse at
    # the referenced SHA (tools/crash_matrix.py).
    from stateright_tpu.artifacts import latest_ckpt_summary

    ckpt_ref = latest_ckpt_summary()
    if ckpt_ref is not None:
        _stderr(
            f"ckpt ref: {ckpt_ref['artifact']} "
            f"clean={ckpt_ref['clean']}"
        )
    # SERVE cross-reference (the resident-service round, same
    # best-effort contract): the newest serve-report artifact — the
    # warm-vs-cold latency-per-query verdict measured at the
    # referenced SHA (tools/serve_report.py, stateright_tpu/serve.py).
    from stateright_tpu.artifacts import latest_serve_summary

    serve_ref = latest_serve_summary()
    if serve_ref is not None:
        _stderr(
            f"serve ref: {serve_ref['artifact']} "
            f"sessions={serve_ref['sessions']}"
        )
    # SLO cross-reference (the live-metrics round, same best-effort
    # contract): the newest service-level-objective gate evaluation —
    # whether the sustained-load p50/p99/refusal/queue-wait/cache-hit
    # objectives held at the referenced SHA (tools/slo_report.py,
    # stateright_tpu/metrics.py).
    from stateright_tpu.artifacts import latest_slo_summary

    slo_ref = latest_slo_summary()
    if slo_ref is not None:
        _stderr(
            f"slo ref: {slo_ref['artifact']} "
            f"ok={slo_ref['ok']}"
        )
    # SOUND cross-reference (the soundness-analyzer round, same
    # best-effort contract): the newest reduction soundness
    # certificate — whether every declared spec/mask the (sym) lanes
    # run was certified at the referenced SHA (analysis/soundness.py).
    from stateright_tpu.artifacts import latest_soundness_summary

    sound_ref = latest_soundness_summary()
    if sound_ref is not None:
        _stderr(
            f"sound ref: {sound_ref['artifact']} "
            f"clean={sound_ref['clean']}"
        )

    # Compile-cache ledger (round 14, checkers/tpu.py): per-lane
    # DELTAS of the process-cumulative compile-or-fetch counters, so
    # each lane's detail names what it paid (cold compiles vs disk
    # hits vs nothing) — the warm/cold attribution the BENCH_r06 chip
    # A/B reads; the provenance block carries the process totals.
    from stateright_tpu.checkers.tpu import compile_ledger_totals

    def _ledger_delta(before, after):
        return {
            k: (round(after[k] - before[k], 6)
                if isinstance(after[k], float)
                else after[k] - before[k])
            for k in ("compiles", "disk_hits", "cold_compiles",
                      "compile_sec", "stage_sec")
        }

    detail = {}
    headline_name, headline_sps = None, 0.0
    loads = tpu_workloads(quick=args.quick)
    for i, (name, spawn, hybrid_spawn, expected) in enumerate(loads):
        ledger_before = compile_ledger_totals()
        # ONE definition of "the traced lane" (the headline), shared
        # by the tracing block and the shard_balance attachment below
        lane_traced = tracer is not None and i == len(loads) - 1
        if lane_traced:
            # Trace the headline lane's timed runs (warm run last, so
            # trace_diff's default last-run view reads the warm one).
            # Artifacts land in a finally: a failed/interrupted run's
            # partial trace is the one needed for diagnosis.
            from stateright_tpu.telemetry import write_artifacts

            try:
                with tracer.activate():
                    checker, sec = time_checker(spawn, runs=args.runs)
            finally:
                if tracer.events:
                    jsonl, chrome = write_artifacts(tracer)
                    detail["trace_artifacts"] = [jsonl, chrome]
                    _stderr(f"trace: wrote {jsonl} + {chrome}")
        else:
            checker, sec = time_checker(spawn, runs=args.runs)
        unique = checker.unique_state_count()
        if unique != expected:
            _stderr(f"ERROR {name}: unique={unique} != expected {expected}")
            sys.exit(1)
        checker.assert_properties()
        sps = unique / sec
        detail[name] = {
            "unique": unique,
            "sec": round(sec, 4),
            "states_per_sec": round(sps),
            # name only — the full cross-reference block lives once
            # in provenance, not N+1 times per artifact line
            **({"lint": lint_ref["artifact"]}
               if lint_ref is not None else {}),
            **({"comms": comms_ref["artifact"]}
               if comms_ref is not None else {}),
            # sharded lanes: routed shuffle volume (the module
            # docstring's promise — recorded where a shuffle exists)
            **({"shuffle_volume": checker.metrics["shuffle_volume"]}
               if "shuffle_volume" in checker.metrics else {}),
        }
        # Codegen-optimizer provenance (round 23): compiled lanes
        # record WHAT the optimizer emitted (fused switch, elided
        # gathers, table widths) via the engine seam — the numbers
        # the parity ratio below is explained by.
        cg = getattr(checker, "codegen_opt", None)
        if cg is not None:
            detail[name]["codegen_opt"] = cg
        # Latency split (round 14): the lane's host dispatch vs
        # sync-floor wall — measured untraced too — plus the lane's
        # compile-cache ledger delta (cold AND warm runs: both ran
        # inside this lane's bracket). The accounting describes the
        # LAST (warm) run, so the share divides by THAT run's own
        # wall (checker.duration_sec()), not the best-of-N `sec`:
        # mixing runs could report sync_share > 1.
        lat = (checker.latency_accounting()
               if hasattr(checker, "latency_accounting") else None)
        if lat is not None:
            run_wall = checker.duration_sec()
            detail[name]["latency"] = {
                **lat,
                "run_wall_sec": round(run_wall, 4),
                "sync_share": (round(lat["fetch_sec"] / run_wall, 4)
                               if run_wall else None),
            }
        ledger = _ledger_delta(ledger_before, compile_ledger_totals())
        detail[name]["compile_cache"] = ledger
        if args.verbose or ledger["compiles"]:
            _stderr(
                f"     compile-cache: {ledger['compiles']} "
                f"compile-or-fetch ({ledger['disk_hits']} disk, "
                f"{ledger['cold_compiles']} cold, "
                f"{ledger['compile_sec']:.2f}s)"
                + (f"; sync floor {lat['fetch_sec']:.3f}s over "
                   f"{lat['chunks']} chunk(s)"
                   if lat is not None else "")
            )
        # Memory ledger (round 12, stateright_tpu/memplan.py): every
        # lane embeds its resident/staging plan totals — the engines
        # compute the plan untraced too (eval_shape, no device work)
        # — and the run peak where the watermark polled it (traced
        # lanes only: polling rides the tracer gate).
        mp = getattr(checker, "memory_plan", None)
        if mp is not None:
            detail[name]["memory_plan"] = {
                "resident_bytes": mp["resident_bytes"],
                "class_peak_bytes": mp["class_peak_bytes"],
                "total_bytes": mp["total_bytes"],
            }
            _stderr(
                f"     memory: resident "
                f"{mp['resident_bytes']:,} B + class peak "
                f"{mp['class_peak_bytes']:,} B = "
                f"{mp['total_bytes']:,} B planned"
                + (f"; device peak "
                   f"{checker.metrics['device_peak_bytes']:,} B"
                   if "device_peak_bytes" in checker.metrics else "")
            )
        if "device_peak_bytes" in getattr(checker, "metrics", {}):
            detail[name]["device_peak_bytes"] = (
                checker.metrics["device_peak_bytes"]
            )
        if lane_traced:
            # a traced MESH lane leaves its skew numbers in the lane
            # detail (single-chip traces have no shard_wave events
            # and skip this)
            from stateright_tpu.telemetry import shard_balance

            bal = shard_balance(tracer.events)
            if bal is not None:
                detail[name]["shard_balance"] = {
                    k: v for k, v in bal.items() if k != "per_wave"
                }
        if name in SYM_LANES:
            # the reduction record (ROADMAP 4(a)): raw space vs the
            # canonical quotient this lane explored, plus — on the
            # rm=5 lane — the live host-DFS-sym oracle A/B (count
            # parity asserted; the deeper parity matrix lives in
            # tests/test_device_symmetry.py)
            raw, rm = SYM_LANES[name]
            detail[name]["symmetry"] = {
                "raw_unique": raw,
                "canonical_unique": unique,
                "reduction_ratio": round(raw / unique, 2),
            }
            # certificate provenance (analysis/soundness.py): the
            # pending BENCH_r06 chip outing must carry proof that
            # the reductions it prices were certified — re-run the
            # analyzer uncached so the wall-time is real, not a
            # memo hit from the spawn gate.
            from stateright_tpu.analysis.soundness import (
                certify_encoding,
            )

            cert = certify_encoding(checker.encoded, use_cache=False)
            detail[name]["symmetry"]["soundness_certified"] = (
                cert.certified
            )
            detail[name]["symmetry"]["soundness_analyzer_sec"] = (
                round(cert.analyzer_sec, 4)
            )
            _stderr(
                f"     symmetry: {raw:,} raw -> {unique:,} canonical "
                f"(x{raw / unique:.1f} reduction); soundness "
                f"{'certified' if cert.certified else 'REFUSED'} "
                f"in {cert.analyzer_sec:.2f}s"
            )
            if rm == 5:
                o_unique, o_sec = bench_sym_host_oracle(rm)
                if o_unique != unique:
                    _stderr(
                        f"ERROR {name}: host DFS sym oracle "
                        f"{o_unique} != device {unique}"
                    )
                    sys.exit(1)
                detail[name]["symmetry"]["host_dfs_sym_sec"] = round(
                    o_sec, 4
                )
        _stderr(
            f"tpu  {name}: unique={unique} sec={sec:.3f} "
            f"states/sec={sps:,.0f}"
        )
        if hasattr(checker, "merge_impl"):
            # merge_impl + merge-stage share (round 10): which
            # visited-dedup implementation this lane ran (pallas |
            # xla fallback) and an isolated re-timing of the dedup
            # stage at the lane's converged class shapes
            # (wavewall.merge_stage_estimate — synthetic keys, same
            # program), so the pending BENCH_r06 chip run can A/B
            # the kernel against these rows with trace_diff. The
            # retired rebuild-sort path is re-timed alongside as the
            # denominator; share_est = dedup_ms x waves / wall.
            from stateright_tpu.wavewall import merge_stage_estimate

            est = merge_stage_estimate(checker)
            waves = checker.metrics.get("waves")
            detail[name]["merge_impl"] = est["impl"]
            detail[name]["merge_stage"] = {
                **est,
                "waves": waves,
                "share_est": (
                    round(est["dedup_ms"] * (waves or 0) / 1000.0
                          / sec, 4)
                    if sec else None
                ),
            }
            _stderr(
                f"     merge[{est['impl']}]: dedup "
                f"{est['dedup_ms']:.2f} ms/wave (sort "
                f"{est['cand_sort_ms']:.2f} + member "
                f"{est['member_ms']:.2f} + wcompact "
                f"{est['winner_compact_ms']:.2f} + append "
                f"{est['append_ms']:.2f}) vs retired rebuild "
                f"{est['rebuild_sort_ms']:.2f}; share~"
                f"{detail[name]['merge_stage']['share_est']}"
            )
        if hybrid_spawn is not None:
            # Sub-100k lanes finish in ~one axon RTT on the wave
            # engine, so their states/sec row reads as hundreds where
            # the product answer (the hybrid racer, usually the host
            # side for these) is single-digit ms — record the hybrid
            # wall time so the ladder tells the truth (VERDICT r5
            # item 7).
            hy, hy_sec, hy_winner = None, float("inf"), None
            for _ in range(args.runs):
                h = hybrid_spawn()
                t0 = time.monotonic()
                h.join()
                dt = time.monotonic() - t0
                if dt < hy_sec:
                    hy_sec, hy_winner = dt, h.winner
                hy = h
            if hy.unique_state_count() != expected:
                _stderr(
                    f"ERROR {name} hybrid: unique="
                    f"{hy.unique_state_count()} != {expected}"
                )
                sys.exit(1)
            detail[name]["hybrid_sec"] = round(hy_sec, 4)
            detail[name]["hybrid_winner"] = hy_winner
            _stderr(
                f"     hybrid: sec={hy_sec:.4f} (winner={hy_winner})"
            )
        if args.verbose:
            _stderr(f"     metrics: {checker.metrics}")
        headline_name, headline_sps = name, sps

    # Compiled-vs-hand parity (round 23, ROADMAP direction 5): every
    # compiled lane embeds the ratio of its pooled-min wall to its
    # hand-encoding denominator's — same pinned state space, so the
    # number is the compiled codegen's gap and nothing else. A
    # post-loop pass (not in-lane) so lane ORDER stays free: the
    # rm=7 denominator runs after its compiled lane to keep the hand
    # lane the traced headline.
    compiled_parity = {}
    for cname, hname in COMPILED_PARITY.items():
        if cname not in detail or hname not in detail:
            continue
        ratio = round(detail[cname]["sec"] / detail[hname]["sec"], 3)
        detail[cname]["parity"] = {
            "hand_lane": hname,
            "hand_sec": detail[hname]["sec"],
            "parity_ratio": ratio,
        }
        compiled_parity[cname] = detail[cname]["parity"]
        _stderr(
            f"parity {cname}: {ratio}x vs {hname} "
            f"({detail[cname]['sec']:.3f}s / "
            f"{detail[hname]['sec']:.3f}s)"
        )

    if not args.quick:
        detail["ttfc"] = bench_ttfc(runs=args.runs)

    # Provenance block (stateright_tpu/artifacts.py): the BENCH
    # artifact the driver captures from this line must name the
    # toolchain/device/SHA it was measured under — a states/sec with
    # no context is not comparable across rounds.
    from stateright_tpu.artifacts import provenance

    print(
        json.dumps(
            {
                "metric": f"unique states/sec ({headline_name}, 1 chip)",
                "value": round(headline_sps),
                "unit": "states/sec",
                "vs_baseline": round(headline_sps / host_sps, 2),
                "sync_floor_ms": sync_floor_ms,
                "provenance": provenance(
                    lane={
                        "headline": headline_name,
                        **({
                            "merge_impl":
                                detail[headline_name]["merge_impl"],
                            "merge_stage":
                                detail[headline_name]["merge_stage"],
                        } if headline_name in detail
                            and "merge_impl" in detail[headline_name]
                            else {}),
                        # the headline's memory ledger totals + run
                        # peak (round 12): the BENCH artifact carries
                        # the numbers a chip run's capacity decisions
                        # read, the way it carries merge_stage
                        **({
                            "memory_plan":
                                detail[headline_name]["memory_plan"],
                        } if headline_name in detail
                            and "memory_plan" in detail[headline_name]
                            else {}),
                        **({
                            "device_peak_bytes":
                                detail[headline_name][
                                    "device_peak_bytes"],
                        } if headline_name in detail
                            and "device_peak_bytes"
                            in detail[headline_name]
                            else {}),
                        # the headline's dispatch/sync-floor split +
                        # the PROCESS compile-cache totals (round 14):
                        # hit-tier counts and the total cold-compile
                        # wall, so warm/cold attribution reads off
                        # the artifact alone
                        **({"latency":
                                detail[headline_name]["latency"]}
                           if headline_name in detail
                           and "latency" in detail[headline_name]
                           else {}),
                        # compiled-vs-hand ratio table (round 23):
                        # the artifact alone answers "how far is the
                        # generic compiler from the hand encodings"
                        **({"compiled_parity": compiled_parity}
                           if compiled_parity else {}),
                        "compile_cache": compile_ledger_totals(),
                        **({"lint": lint_ref}
                           if lint_ref is not None else {}),
                        **({"comms": comms_ref}
                           if comms_ref is not None else {}),
                        **({"ckpt": ckpt_ref}
                           if ckpt_ref is not None else {}),
                        **({"serve": serve_ref}
                           if serve_ref is not None else {}),
                        **({"slo": slo_ref}
                           if slo_ref is not None else {}),
                        **({"soundness": sound_ref}
                           if sound_ref is not None else {}),
                    }
                ),
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    main()
