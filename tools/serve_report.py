#!/usr/bin/env python
"""Serve report: the latency-per-query view of a resident-service
trace — ROADMAP direction 4's first-class metric, rendered.

Reads a ``TRACE_r*.jsonl`` artifact exported by the resident checking
service (stateright_tpu/serve.py ``write_trace`` — one run index per
session, bracketed by ``session_begin``/``session_end`` service
events) and renders the tables the serving story is judged by:

* **per-session table** — kind, lane, state, time-to-verdict, queue
  wait (the FIFO device gate), admission wait, compile tier counts,
  warm-start / resumed-from-wave, counts, and the Explorer
  cache-hit ratio for explorer sessions,
* **warm-vs-cold pairing** — repeat queries of one program key
  against their cold first query: the time-to-verdict delta with the
  ledger attribution split between the compile tier (build walls)
  and dispatch proper (``dispatch_net_sec``) — the acceptance read
  for "the warm query is faster BECAUSE the compile amortized, not
  because dispatch changed",
* **batch occupancy** — fused wave-dispatch groups
  (``--batch-sessions``): which sessions shared one device dispatch,
  how many fused chunks they rode, and the amortized floor per query
  (each member's dispatch+sync overhead is its 1/N_active share of
  the fused walls),
* **LRU evictions** — programs the byte budget dropped, and
  snapshots the warm-start spool budget dropped.

The derived summary comes from ``serve.serve_summary`` (the block
bench provenance embeds via ``artifacts.latest_serve_summary``), so
this report and those artifacts cannot disagree. ``--json`` writes an
auto-numbered ``SERVE_r*.json`` (its own round sequence — SERVE_r01
first — cross-referenced to the TRACE it was derived from; numbering
via stateright_tpu/artifacts.py).

Usage:
  python tools/serve_report.py TRACE_r30.jsonl
  python tools/serve_report.py TRACE_r30.jsonl --json

Exit status: 0 (report printed), 2 bad input / no session events in
the trace (not a service trace).
"""

import argparse
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _sec(x) -> str:
    if x is None:
        return "-"
    return f"{x:,.4f} s" if x >= 0.001 else f"{x * 1e3:,.3f} ms"


def format_report(summary: dict) -> str:
    sessions = summary["sessions"]
    lines = [
        f"serve report: {len(sessions)} session(s)",
        "",
        f"  {'#':>3s} {'kind':<9s} {'lane':<26s} {'state':<8s} "
        f"{'ttv':>12s} {'queue':>10s} {'tiers':<22s} "
        f"{'warm':<5s} {'unique':>9s}",
    ]
    for s in sessions:
        tiers = ",".join(
            f"{k}:{v}" for k, v in sorted(
                (s.get("builds") or {}).get("tiers", {}).items()
            )
        ) or "-"
        warm = "yes" if s.get("warm_start") else "no"
        if s.get("resumed_from_wave") is not None:
            warm += f"@w{s['resumed_from_wave']}"
        lines.append(
            f"  {s['session']:>3d} {s['kind']:<9s} "
            f"{(s.get('lane') or '')[:26]:<26s} "
            f"{(s.get('state') or '?'):<8s} "
            f"{_sec(s.get('time_to_verdict_sec')):>12s} "
            f"{_sec(s.get('queue_wait_sec')):>10s} {tiers:<22s} "
            f"{warm:<5s} "
            f"{s['unique'] if s.get('unique') is not None else '-':>9}"
        )
        if s.get("error"):
            lines.append(f"      ERROR: {s['error']}")
        ex = s.get("explorer")
        if ex:
            hits = ex["cache_hits"]
            n = ex["requests"]
            lines.append(
                f"      explorer: {n} request(s), {hits} cache "
                f"hit(s) ({hits / n:.0%})" if n else
                "      explorer: 0 requests"
            )

    # the aggregate quantiles ride the ONE shared implementation
    # (stateright_tpu/metrics.py quantile — the same function
    # serve_loadtest.py and the SLO gate use), so the report and the
    # gate cannot disagree on what "p99" means
    from stateright_tpu.metrics import quantile

    ttvs = [s.get("time_to_verdict_sec") for s in sessions
            if s.get("time_to_verdict_sec") is not None]
    if len(ttvs) >= 2:
        lines.append("")
        lines.append(
            f"  time-to-verdict: p50 {_sec(quantile(ttvs, 0.50))} / "
            f"p99 {_sec(quantile(ttvs, 0.99))} "
            f"across {len(ttvs)} session(s)"
        )

    wvc = summary.get("warm_vs_cold") or []
    if wvc:
        lines.append("")
        lines.append("warm vs cold (repeat queries of one program):")
        for p in wvc:
            lines.append(
                f"  program {p['program_key']}: cold #"
                f"{p['cold_session']} ttv {_sec(p['cold_ttv_sec'])}"
                f" -> warm #{p['warm_session']} ttv "
                f"{_sec(p['warm_ttv_sec'])} "
                f"(delta {_sec(p['ttv_delta_sec'])}; compile-tier "
                f"{_sec(p['compile_delta_sec'])}, dispatch "
                f"{_sec(abs(p['dispatch_net_delta_sec']))} "
                f"{'less' if p['dispatch_net_delta_sec'] >= 0 else 'more'}"
                f"; waves {p.get('waves_cold')} -> "
                f"{p.get('waves_warm')}"
                + (", warm-start" if p.get("warm_start") else "")
                + ")"
            )

    batches = summary.get("batches") or []
    if batches:
        lines.append("")
        lines.append(
            "batch occupancy (fused wave dispatch, "
            "stateright_tpu/batch.py):"
        )
        lines.append(
            f"  {'grp':>4s} {'size':>4s} {'chunks':>6s} "
            f"{'sessions':<18s} {'per-query overhead':>19s}"
        )
        for g in batches:
            sess = ",".join(f"#{s}" for s in g["sessions"])
            lines.append(
                f"  {g['group']:>4d} "
                f"{g.get('size') or len(g['sessions']):>4d} "
                f"{g.get('chunks') if g.get('chunks') is not None else '-':>6} "
                f"{sess:<18s} "
                f"{_sec(g.get('per_query_overhead_sec')):>19s}"
            )
        lines.append("")
        lines.append(
            "amortized floor per query (each member's dispatch+sync "
            "is its 1/N_active share of the fused walls):"
        )
        lines.append(
            f"  {'grp':>4s} {'#':>4s} {'waves':>6s} "
            f"{'dispatch':>12s} {'fetch':>12s} {'overhead':>12s} "
            f"{'ttv':>12s}"
        )
        for g in batches:
            for m in g["members"]:
                lines.append(
                    f"  {g['group']:>4d} {m['session']:>4d} "
                    f"{m.get('waves') if m.get('waves') is not None else '-':>6} "
                    f"{_sec(m.get('dispatch_net_sec')):>12s} "
                    f"{_sec(m.get('fetch_sec')):>12s} "
                    f"{_sec(m.get('overhead_sec')):>12s} "
                    f"{_sec(m.get('time_to_verdict_sec')):>12s}"
                )

    ev = summary.get("evictions") or []
    if ev:
        lines.append("")
        lines.append("program-LRU evictions:")
        for e in ev:
            lines.append(
                f"  key {e.get('key')}: {e.get('bytes'):,} B "
                f"(session run {e.get('run')})"
            )
    sev = summary.get("snapshot_evictions") or []
    if sev:
        lines.append("")
        lines.append("snapshot-spool evictions (byte-budget LRU):")
        for e in sev:
            lines.append(
                f"  key {e.get('key')}: {e.get('bytes'):,} B "
                f"(session run {e.get('run')})"
            )
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(
        description="per-session latency-per-query report over a "
        "resident-service TRACE"
    )
    ap.add_argument("trace", help="TRACE_r*.jsonl artifact (from "
                    "CheckService.write_trace)")
    ap.add_argument(
        "--json", action="store_true",
        help="also write an auto-numbered SERVE_r*.json artifact",
    )
    ap.add_argument(
        "--root", default=None,
        help="artifact directory for --json (default: the repo root)",
    )
    args = ap.parse_args()

    from stateright_tpu.serve import serve_summary, \
        write_serve_artifact
    from stateright_tpu.telemetry import load_trace, validate_events

    try:
        events = load_trace(args.trace)
        validate_events(events)
    except (OSError, ValueError) as exc:
        print(f"serve_report: bad input: {exc}", file=sys.stderr)
        sys.exit(2)

    summary = serve_summary(events)
    if summary is None:
        print(
            "serve_report: no session events in this trace — export "
            "one from a resident service "
            "(stateright_tpu/serve.py CheckService.write_trace, or "
            "POST /.serve/trace on a running daemon)",
            file=sys.stderr,
        )
        sys.exit(2)
    print(format_report(summary))
    if args.json:
        summary = dict(summary, trace=os.path.basename(args.trace))
        path = write_serve_artifact(summary, root=args.root)
        print(f"\nwrote {path}")


if __name__ == "__main__":
    main()
