#!/usr/bin/env python
"""Shard-balance report: the human-readable view of a mesh TRACE.

Reads a ``TRACE_r*.jsonl`` run-telemetry artifact whose run carries
``shard_wave`` events (a traced run of either sharded engine —
``spawn_tpu_sharded_sortmerge`` / ``spawn_tpu_sharded``) and renders
the numbers that decide whether the (owner, fp)-sort shuffle scales
(ROADMAP direction 1):

* **per-wave skew** — frontier and candidate max/mean balance across
  shards (1.00 = perfect; n_shards = one shard carries everything),
* **shuffle volume** — rows routed off-shard per wave and cumulative
  (plus bytes, priced from the lane's routed-tile width),
* **dest-tile headroom** — peak per-destination fill vs the lossless
  ``Bd`` cap that gates ``all_to_all`` correctness (fill past the cap
  is ``c_overflow``; the report warns as it approaches),
* **occupancy trajectory** — each shard's visited count vs the
  per-shard capacity.

The derived metrics come from ``telemetry.shard_balance`` (the same
summary the MULTICHIP dryrun tail and traced bench lanes embed), so
this report and those artifacts cannot disagree.

Usage:
  python tools/shard_report.py TRACE_r16.jsonl
  python tools/shard_report.py TRACE_r16.jsonl --run 0
  python tools/shard_report.py TRACE_r16.jsonl --waves 50

Exit status: 0 (report printed, warnings included), 2 bad input /
no shard events in the trace.
"""

import argparse
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _fmt_skew(x):
    return "-" if x is None else f"{x:.2f}"


def format_report(bal: dict, max_waves: int) -> str:
    lines = [
        f"shard balance: run #{bal['run']}, {bal['n_shards']} shards, "
        f"{bal['waves']} waves",
        "",
        f"{'wave':>5s} {'frontier':>9s} {'f-skew':>7s} "
        f"{'cands':>9s} {'c-skew':>7s} {'routed':>8s} "
        f"{'fill/cap':>12s} {'util':>6s}",
    ]
    waves = bal["per_wave"]
    shown = waves if len(waves) <= max_waves else waves[:max_waves]
    for m in shown:
        util = "-" if m["dest_util"] is None else f"{m['dest_util']:.0%}"
        lines.append(
            f"{m['wave']:5d} {m['frontier_total']:9d} "
            f"{_fmt_skew(m['frontier_skew']):>7s} "
            f"{m['candidates_total']:9d} "
            f"{_fmt_skew(m['candidate_skew']):>7s} "
            f"{m['routed_rows']:8d} "
            f"{m['dest_fill_peak']:5d}/{m['dest_cap']:<6d} "
            f"{util:>6s}"
        )
    if len(waves) > max_waves:
        lines.append(f"  ... {len(waves) - max_waves} more waves "
                     "(--waves N to widen)")
    lines.append("")
    wf = bal["frontier_skew_worst"]
    wc = bal["candidate_skew_worst"]
    lines.append(
        "worst-wave skew: frontier "
        + ("-" if wf is None
           else f"{wf['skew']:.2f}x (wave {wf['wave']})")
        + ", candidates "
        + ("-" if wc is None
           else f"{wc['skew']:.2f}x (wave {wc['wave']})")
        + ", size-weighted frontier "
        + _fmt_skew(bal["frontier_skew_weighted"])
        + "x"
    )
    rb = bal["routed_bytes_total"]
    lines.append(
        f"cumulative shuffle: {bal['routed_rows_total']:,} rows "
        "routed off-shard"
        + (f" ({rb / 1e6:.2f} MB of routed-tile payload)"
           if rb is not None else "")
        + f"; {bal['recv_rows_total']:,} rows received "
        "(incl. self-owned)"
    )
    df = bal["dest_fill_worst"]
    if df is not None:
        lines.append(
            f"dest-tile headroom: peak fill {df['fill']}/{df['cap']} "
            f"({df['util']:.0%}, wave {df['wave']}) vs the lossless "
            "Bd cap"
        )
    cs = bal.get("comms_static")
    if cs is not None:
        # static-vs-runtime comms reconciliation (comms-lint, PERF.md
        # §comms-lint): measured routed rows x the static per-row
        # price vs the per-wave all_to_all exchange ceiling.
        # bound_util is None on a trace whose waves all report
        # dest_cap=0 (truncated/foreign traces) — the producer admits
        # the case, so the report must too.
        util = (
            f"= {cs['bound_util']:.1%} of"
            if cs["bound_util"] is not None else "vs"
        )
        lines.append(
            f"comms static: {cs['row_bytes']} B/row routed-tile "
            f"price; measured {cs['measured_routed_bytes'] / 1e6:.2f}"
            f" MB {util} the "
            f"{cs['bytes_bound_total'] / 1e6:.2f} MB static "
            "all_to_all exchange bound"
        )
    vis = bal["visited_per_shard"]
    cap = bal["shard_capacity"]
    occ = (
        f"; occupancy max {bal['occupancy_max']:.1%} of "
        f"{cap}/shard" if bal["occupancy_max"] is not None else ""
    )
    lines.append(
        f"visited per shard: min {min(vis)}, max {max(vis)} "
        f"(balance {_fmt_skew(bal['visited_skew'])}x){occ}"
    )
    if bal["warnings"]:
        lines.append("")
        for w in bal["warnings"]:
            lines.append(f"WARNING: {w}")
    else:
        lines.append("no headroom/skew warnings")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(
        description="shard balance/routing report over a mesh TRACE"
    )
    ap.add_argument("trace", help="TRACE_r*.jsonl artifact")
    ap.add_argument(
        "--run", type=int, default=None,
        help="run index inside the trace (default: the last run)",
    )
    ap.add_argument(
        "--waves", type=int, default=40,
        help="max per-wave rows to print (default 40)",
    )
    args = ap.parse_args()

    from stateright_tpu.telemetry import (
        load_trace,
        shard_balance,
        validate_events,
    )

    try:
        events = load_trace(args.trace)
        validate_events(events)
    except (OSError, ValueError) as exc:
        print(f"shard_report: bad input: {exc}", file=sys.stderr)
        sys.exit(2)

    runs = sorted({e["run"] for e in events
                   if e["ev"] == "run_begin"})
    if args.run is not None and args.run not in runs:
        print(
            f"shard_report: run {args.run} not in this trace "
            f"(runs: {runs})",
            file=sys.stderr,
        )
        sys.exit(2)

    bal = shard_balance(events, run=args.run)
    if bal is None:
        print(
            "shard_report: no shard_wave events in this trace — "
            "trace a SHARDED engine run "
            "(spawn_tpu_sharded_sortmerge / spawn_tpu_sharded)",
            file=sys.stderr,
        )
        sys.exit(2)
    print(format_report(bal, args.waves))


if __name__ == "__main__":
    main()
