#!/usr/bin/env python
"""Per-stage / per-wave profile of the sort-merge engine (VERDICT r2 #1).

Two parts:

A. **Wave profile** — run 2pc rm=7/8 with ``waves_per_sync=1`` and a
   reporter that records wall-clock + unique-count per chunk (= per
   wave), so we see exactly which waves cost what and how much of the
   run is peak-wave vs. tail-wave.

B. **Primitive microbench** at the rm=8 shapes — lax.sort at the
   engine's actual row counts, gathers, and a 2-limb binary-search
   membership probe (the sort#2/#3 replacement candidate).

The axon-tunneled TPU hides execution behind dispatch (~the same
0.02ms shows for any op if timed naively) and a host readback costs
hundreds of ms, so each measured op runs REPS times inside one jitted
``fori_loop`` (inputs perturbed per iteration so XLA cannot CSE the
repeats away) and the loop's scalar checksum is fetched once; reported
time = (total - empty-loop baseline) / REPS.

Usage: python tools/profile_sortmerge.py [--skip-wave] [--skip-micro] [--rm8]
"""

import argparse
import time

REPS = 16


def _timed_loop(build_body, args, reps=REPS):
    """Time one application of build_body's op, amortized over `reps`
    sequential applications inside a single jitted program."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def run(*arrs):
        def body(i, carry):
            return build_body(i, carry)

        out = lax.fori_loop(0, reps, body, arrs)
        return sum(jnp.sum(a[..., :1].astype(jnp.uint32)) for a in out)

    f = jax.jit(run)
    s = f(*args)
    float(s)  # warm + compile + fetch
    best = float("inf")
    for _ in range(3):
        t0 = time.monotonic()
        float(f(*args))
        best = min(best, time.monotonic() - t0)
    return best


def _baseline(args):
    """Empty-loop + fetch cost with the same carry shapes."""
    return _timed_loop(lambda i, c: c, args)


def microbench():
    import jax
    import jax.numpy as jnp
    from jax import lax

    print("\n## primitive microbench (rm=8 shapes, per-op ms, "
          f"amortized over {REPS} in-loop reps)")
    key = jax.random.PRNGKey(0)

    def rnd(shape, i=0):
        return jax.random.bits(jax.random.fold_in(key, i), shape,
                               dtype=jnp.uint32)

    # lax.sort at engine row counts
    for n, lanes, label in [
        (1 << 21, 2, "sort C=2^21 2-lane"),
        (6 << 20, 3, "sort C+B=6M 3-lane (sort#2)"),
        (6 << 20, 2, "sort C+B=6M 2-lane (sort#3)"),
        (22 << 20, 3, "sort F*K=22M 3-lane (sort#1 rm=8 tiles=1)"),
        (1 << 22, 3, "sort B=4M 3-lane"),
        (1 << 20, 3, "sort 1M 3-lane"),
        (1 << 17, 3, "sort 128k 3-lane"),
    ]:
        arrs = tuple(rnd((n,), i) for i in range(lanes))

        def body(i, c, lanes=lanes):
            c0 = c[0] ^ i.astype(jnp.uint32)  # defeat CSE
            out = lax.sort((c0,) + c[1:], num_keys=min(2, lanes))
            return out

        dt = _timed_loop(body, arrs) - _baseline(arrs)
        print(f"  {label:48s} {dt/REPS*1000:8.2f} ms")

    # gathers
    for src_n, idx_n, w, label in [
        (22 << 20, 1 << 22, 2, "gather 4M rows W=2 from 22M (st=flat[s_row])"),
        (1 << 22, 1 << 19, 2, "gather 512k rows W=2 from 4M (next_frontier)"),
        (1 << 21, 1 << 22, 1, "gather 4M scalars from 2M (binsearch step)"),
    ]:
        src = rnd((src_n, w) if w > 1 else (src_n,))
        idx = jax.random.randint(key, (idx_n,), 0, src_n, dtype=jnp.int32)

        def body(i, c, src_n=src_n):
            src, idx = c
            idx2 = (idx + i) % src_n  # defeat CSE
            g = src[idx2]
            # fold the gather back into idx so the loop is sequential
            upd = (jnp.sum(g.astype(jnp.uint32)) & jnp.uint32(1)).astype(
                jnp.int32)
            return src, idx + upd

        dt = _timed_loop(body, (src, idx)) - _baseline((src, idx))
        print(f"  {label:48s} {dt/REPS*1000:8.2f} ms")

    # 2-limb binary-search membership into sorted C=2^21
    C = 1 << 21
    v_hi = jnp.sort(rnd((C,), 1))
    v_lo = rnd((C,), 2)
    for B, label in [
        (1 << 22, "binsearch 4M queries into sorted 2M (21 it)"),
        (1 << 19, "binsearch 512k queries into sorted 2M (21 it)"),
    ]:
        q_hi, q_lo = rnd((B,), 3), rnd((B,), 4)

        def body(i, c, B=B):
            v_hi, v_lo, q_hi, q_lo = c
            qh = q_hi ^ i.astype(jnp.uint32)
            lo = jnp.zeros(B, jnp.int32)
            hi = jnp.full(B, C, jnp.int32)

            def step(_, lh):
                lo, hi = lh
                mid = (lo + hi) // 2
                m_hi, m_lo = v_hi[mid], v_lo[mid]
                lt = (m_hi < qh) | ((m_hi == qh) & (m_lo < q_lo))
                return jnp.where(lt, mid + 1, lo), jnp.where(lt, hi, mid)

            lo, hi = lax.fori_loop(0, 21, step, (lo, hi))
            idx = jnp.clip(lo, 0, C - 1)
            found = (v_hi[idx] == qh) & (v_lo[idx] == q_lo)
            return (v_hi, v_lo, q_hi + found.astype(jnp.uint32), q_lo)

        args = (v_hi, v_lo, q_hi, q_lo)
        dt = _timed_loop(body, args) - _baseline(args)
        print(f"  {label:48s} {dt/REPS*1000:8.2f} ms")


def wave_profile(rm, capacity, frontier_capacity, cand_capacity):
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys
    from stateright_tpu.report import Reporter

    rows = []

    class Rec(Reporter):
        def __init__(self):
            self.last = time.monotonic()

        def delay(self):
            return 0.0

        def report_checking(self, data):
            now = time.monotonic()
            rows.append((now - self.last, data.unique_states, data.max_depth))
            self.last = now

    def spawn():
        return TwoPhaseSys(rm_count=rm).checker().spawn_tpu_sortmerge(
            track_paths=False,
            capacity=capacity,
            frontier_capacity=frontier_capacity,
            cand_capacity=cand_capacity,
            waves_per_sync=1,
        )

    spawn().join()  # warm run (compile)
    rows.clear()
    c2 = spawn()
    rec = Rec()
    t0 = time.monotonic()
    c2._ensure_run(rec)
    total = time.monotonic() - t0
    # The sync loop breaks on done before reporting, so the final wave
    # never reaches the reporter — append it from the checker's state.
    rows.append((time.monotonic() - rec.last, c2.unique_state_count(),
                 c2.max_depth()))
    print(f"\n## wave profile: 2pc rm={rm}  (total {total:.3f}s incl "
          f"per-wave sync, unique={c2.unique_state_count()})")
    prev_u = 0
    for i, (dt, u, d) in enumerate(rows):
        print(f"  wave {i:3d}: {dt*1000:8.1f} ms  new={u - prev_u:8d}  "
              f"unique={u:8d} depth={d}")
        prev_u = u


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-wave", action="store_true")
    ap.add_argument("--skip-micro", action="store_true")
    ap.add_argument("--rm8", action="store_true", help="include rm=8 profile")
    args = ap.parse_args()

    import jax

    print(f"backend: {jax.devices()}")
    if not args.skip_micro:
        microbench()
    if not args.skip_wave:
        wave_profile(7, 1 << 19, 1 << 16, 1 << 19)
        if args.rm8:
            wave_profile(8, 1 << 21, 1 << 19, 1 << 22)


if __name__ == "__main__":
    main()
