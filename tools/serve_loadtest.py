#!/usr/bin/env python
"""Serve load test: the wave-batching A/B — N concurrent clients,
batched fused dispatch vs the FIFO-serial baseline.

Runs the SAME small-model fleet twice against two resident services
(stateright_tpu/serve.py):

* **batched** — ``batch_sessions=N``: the fleet rendezvouses in one
  compatibility class and rides ONE fused wave dispatch
  (stateright_tpu/batch.py), each session billed its 1/N_active
  share of the fused dispatch+sync walls,
* **fifo-serial** — batching off: the round-18 baseline, whole
  chunks FIFO-interleaved, every session paying the full per-chunk
  sync floor alone.

Counts must be bit-identical across both arms (they are asserted).
The headline is **per-query dispatch+sync overhead** — each
session's ``dispatch_net_sec + fetch_sec`` from the latency ledger,
compile already subtracted, so the delta is attributed to the fused
dispatch and not to compile amortization — plus p50/p99
time-to-verdict for both arms.

``--json`` exports the batched service's TRACE_r* pair and writes an
auto-numbered ``SERVE_r*.json`` whose summary embeds the
``fifo_baseline`` block, the ``latency_quantiles``, and the
``loadtest`` headline (clients, lane, amortization_x) that bench
provenance surfaces via ``artifacts.latest_serve_summary``.

Usage:
  JAX_PLATFORMS=cpu python tools/serve_loadtest.py
  JAX_PLATFORMS=cpu python tools/serve_loadtest.py --clients=4 \\
      --lane="2pc check-tpu 4" --json

Exit status: 0 on success (amortization printed), 1 when any session
errors or counts diverge between the arms.
"""

import argparse
import os
import sys
import tempfile
import threading

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _quantile(values, q):
    """Linear-interpolated quantile of a small sample (no numpy
    dependency for the report path)."""
    if not values:
        return None
    xs = sorted(values)
    pos = q * (len(xs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    return round(xs[lo] + (xs[hi] - xs[lo]) * (pos - lo), 6)


def _run_fleet(service, lane_argv, n):
    """N concurrent client threads submitting the same lane; returns
    the sessions in submission order."""
    results = {}

    def run(i):
        results[i] = service.check(list(lane_argv))

    threads = [
        threading.Thread(target=run, args=(i,)) for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return [results[i] for i in range(n)]


def _arm_stats(summary):
    """Per-session overhead rows + the arm's aggregate: the latency
    ledger's dispatch_net+fetch (compile subtracted) and the ttv
    quantiles."""
    rows = []
    for s in summary["sessions"]:
        overhead = ((s.get("dispatch_net_sec") or 0.0)
                    + (s.get("fetch_sec") or 0.0))
        rows.append(dict(
            session=s["session"],
            unique=s.get("unique"),
            waves=s.get("waves"),
            batch=s.get("batch"),
            time_to_verdict_sec=s.get("time_to_verdict_sec"),
            dispatch_net_sec=s.get("dispatch_net_sec"),
            fetch_sec=s.get("fetch_sec"),
            overhead_sec=round(overhead, 6),
            compile_wall_sec=(s.get("builds") or {}).get("wall_sec"),
        ))
    ttvs = [r["time_to_verdict_sec"] for r in rows
            if r["time_to_verdict_sec"] is not None]
    ov = [r["overhead_sec"] for r in rows]
    return dict(
        sessions=rows,
        per_query_overhead_sec=(
            round(sum(ov) / len(ov), 6) if ov else None
        ),
        ttv_p50_sec=_quantile(ttvs, 0.50),
        ttv_p99_sec=_quantile(ttvs, 0.99),
    )


def main():
    ap = argparse.ArgumentParser(
        description="N-client wave-batching A/B against the "
        "resident checking service"
    )
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument(
        "--lane", default="2pc check-tpu 4",
        help='lane argv, e.g. "2pc check-tpu 4" (default)',
    )
    ap.add_argument(
        "--json", action="store_true",
        help="export the batched TRACE_r* pair and write an "
        "auto-numbered SERVE_r*.json with the A/B embedded",
    )
    ap.add_argument(
        "--root", default=None,
        help="artifact directory for --json (default: the repo root)",
    )
    args = ap.parse_args()
    lane = args.lane.split()

    from stateright_tpu.serve import (
        CheckService,
        serve_summary,
        write_serve_artifact,
    )

    print(
        f"serve loadtest: {args.clients} concurrent clients x "
        f"'{args.lane}' (batched vs fifo-serial)"
    )

    with tempfile.TemporaryDirectory() as spool:
        batched_svc = CheckService(
            spool_dir=os.path.join(spool, "batched"),
            warm_start=False,
            batch_sessions=args.clients,
            batch_window_sec=60.0,
        )
        batched_sessions = _run_fleet(batched_svc, lane, args.clients)
        fifo_svc = CheckService(
            spool_dir=os.path.join(spool, "fifo"),
            warm_start=False,
        )
        fifo_sessions = _run_fleet(fifo_svc, lane, args.clients)

        for arm, sessions in (("batched", batched_sessions),
                              ("fifo", fifo_sessions)):
            for s in sessions:
                if s.state != "done":
                    print(f"{arm} session {s.id} failed: {s.error}",
                          file=sys.stderr)
                    return 1
        counts = {s.unique for s in batched_sessions} | \
            {s.unique for s in fifo_sessions}
        if len(counts) != 1:
            print(f"count divergence across arms: {counts}",
                  file=sys.stderr)
            return 1

        summary = serve_summary(batched_svc.events())
        fifo_summary = serve_summary(fifo_svc.events())
        batched = _arm_stats(summary)
        fifo = _arm_stats(fifo_summary)
        amortization = (
            round(fifo["per_query_overhead_sec"]
                  / batched["per_query_overhead_sec"], 2)
            if batched["per_query_overhead_sec"] else None
        )

        print(f"  counts: unique={counts.pop():,} on every session, "
              "both arms")
        for label, arm in (("batched", batched),
                           ("fifo-serial", fifo)):
            print(
                f"  {label:<12s} per-query dispatch+sync "
                f"{arm['per_query_overhead_sec']:.4f} s | ttv p50 "
                f"{arm['ttv_p50_sec']:.4f} s p99 "
                f"{arm['ttv_p99_sec']:.4f} s"
            )
        print(f"  amortization: {amortization}x lower per-query "
              "overhead under the fused dispatch")

        summary["fifo_baseline"] = fifo
        summary["latency_quantiles"] = dict(
            batched={k: batched[k]
                     for k in ("ttv_p50_sec", "ttv_p99_sec")},
            fifo_serial={k: fifo[k]
                         for k in ("ttv_p50_sec", "ttv_p99_sec")},
        )
        summary["loadtest"] = dict(
            clients=args.clients,
            lane=args.lane,
            amortization_x=amortization,
            batched_per_query_overhead_sec=(
                batched["per_query_overhead_sec"]
            ),
            fifo_per_query_overhead_sec=(
                fifo["per_query_overhead_sec"]
            ),
        )
        if args.json:
            jsonl, _chrome = batched_svc.write_trace(root=args.root)
            summary["trace"] = os.path.basename(jsonl)
            path = write_serve_artifact(summary, root=args.root)
            print(f"\nwrote {jsonl}\nwrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
