#!/usr/bin/env python
"""Serve load test: the wave-batching A/B and the sustained
ramp→spike→drain SLO run against the resident checking service.

**A/B mode (default)** — N concurrent clients, batched fused dispatch
vs the FIFO-serial baseline. Runs the SAME small-model fleet twice
against two resident services (stateright_tpu/serve.py):

* **batched** — ``batch_sessions=N``: the fleet rendezvouses in one
  compatibility class and rides ONE fused wave dispatch
  (stateright_tpu/batch.py), each session billed its 1/N_active
  share of the fused dispatch+sync walls,
* **fifo-serial** — batching off: the round-18 baseline, whole
  chunks FIFO-interleaved, every session paying the full per-chunk
  sync floor alone.

Counts must be bit-identical across both arms (they are asserted).
The headline is **per-query dispatch+sync overhead** — each
session's ``dispatch_net_sec + fetch_sec`` from the latency ledger,
compile already subtracted, so the delta is attributed to the fused
dispatch and not to compile amortization — plus p50/p99
time-to-verdict for both arms.

**Sustained mode (``--sustained``)** — the live-metrics/SLO evidence
run (ISSUE 19, ROADMAP direction 2(c)): ONE resident service behind
its real HTTP server (the same ``make_server`` surface ``python -m
stateright_tpu serve`` runs), driven through ``POST /.check`` across
three traffic phases — **ramp** (light), **spike** (concurrent
fleet), **drain** (light again). Mid-spike the tool scrapes ``GET
/.metrics`` and asserts the live registry serves the named families
(queue depth/wait, admission decisions, the time-to-verdict
histogram, compile-tier hits, eviction counters) plus the compact
``/.status`` metrics block. Afterward it reports per-phase p50/p99
time-to-verdict BOTH ways — exact (``metrics.quantile`` over the
sample) and streaming (bucket-interpolated over a
``metrics.Histogram``) — evaluates the declarative SLO spec
(``metrics.evaluate_slo`` over ``slo_observed``), and asserts every
served session's count is bit-identical to a solo run of the same
lane on a fresh service. ``--json`` exports the TRACE pair, a
``SERVE_r*.json`` with the sustained block and the registry snapshot
embedded, and the ``SLO_r*.json`` gate evaluation bench provenance
surfaces via ``artifacts.latest_slo_summary``.

Usage:
  JAX_PLATFORMS=cpu python tools/serve_loadtest.py
  JAX_PLATFORMS=cpu python tools/serve_loadtest.py --clients=4 \\
      --lane="2pc check-tpu 4" --json
  JAX_PLATFORMS=cpu python tools/serve_loadtest.py --sustained \\
      --lane="2pc check-tpu 4" --ramp=2 --spike=4 --drain=2 \\
      --slo-ttv-p99=120 --json

Exit status: 0 on success, 1 when any session errors, counts diverge
from the solo baseline, a named metrics family is missing from the
live scrape, or the SLO gate fails.
"""

import argparse
import json as _json
import os
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

#: the families the acceptance scrape asserts a live /.metrics serves
#: under sustained load (ISSUE 19)
REQUIRED_FAMILIES = (
    "stpu_serve_queue_depth",
    "stpu_serve_queue_wait_seconds",
    "stpu_serve_admission_total",
    "stpu_time_to_verdict_seconds",
    "stpu_program_builds_total",
    "stpu_serve_program_evictions_total",
    "stpu_serve_snapshot_evictions_total",
)


def _run_fleet(service, lane_argv, n):
    """N concurrent client threads submitting the same lane; returns
    the sessions in submission order."""
    results = {}

    def run(i):
        results[i] = service.check(list(lane_argv))

    threads = [
        threading.Thread(target=run, args=(i,)) for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return [results[i] for i in range(n)]


def _arm_stats(summary):
    """Per-session overhead rows + the arm's aggregate: the latency
    ledger's dispatch_net+fetch (compile subtracted) and the ttv
    quantiles (the SHARED exact implementation,
    stateright_tpu/metrics.py quantile)."""
    from stateright_tpu.metrics import quantile

    rows = []
    for s in summary["sessions"]:
        overhead = ((s.get("dispatch_net_sec") or 0.0)
                    + (s.get("fetch_sec") or 0.0))
        rows.append(dict(
            session=s["session"],
            unique=s.get("unique"),
            waves=s.get("waves"),
            batch=s.get("batch"),
            time_to_verdict_sec=s.get("time_to_verdict_sec"),
            dispatch_net_sec=s.get("dispatch_net_sec"),
            fetch_sec=s.get("fetch_sec"),
            overhead_sec=round(overhead, 6),
            compile_wall_sec=(s.get("builds") or {}).get("wall_sec"),
        ))
    ttvs = [r["time_to_verdict_sec"] for r in rows
            if r["time_to_verdict_sec"] is not None]
    ov = [r["overhead_sec"] for r in rows]
    return dict(
        sessions=rows,
        per_query_overhead_sec=(
            round(sum(ov) / len(ov), 6) if ov else None
        ),
        ttv_p50_sec=quantile(ttvs, 0.50),
        ttv_p99_sec=quantile(ttvs, 0.99),
    )


# -- sustained ramp -> spike -> drain (the SLO evidence run) --------------


def _post_check(port, lane_argv):
    """One client query through the live HTTP surface (the
    ``--connect`` endpoint): returns the response dict."""
    body = _json.dumps({"argv": list(lane_argv)}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/.check", data=body,
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req) as r:
        return _json.loads(r.read())


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}"
    ) as r:
        return r.read().decode()


def _phase_fleet(port, lane_argv, n):
    """N concurrent HTTP clients; returns their response dicts in
    submission order."""
    results = {}

    def run(i):
        results[i] = _post_check(port, lane_argv)

    threads = [
        threading.Thread(target=run, args=(i,)) for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return [results[i] for i in range(n)]


def run_sustained(lane, phases, slo_spec, json_out=False, root=None):
    """The sustained-load SLO run (importable: the metrics smoke test
    drives it in-process). ``phases`` is ``[(name, clients), ...]``;
    returns ``(exit_code, doc)`` where ``doc`` is the sustained
    summary block (also written into SERVE_r*/SLO_r* when
    ``json_out``)."""
    from stateright_tpu.metrics import (
        Histogram,
        evaluate_slo,
        quantile,
        slo_observed,
        write_slo_artifact,
    )
    from stateright_tpu.serve import (
        CheckService,
        serve_summary,
        write_serve_artifact,
    )

    failures = []
    with tempfile.TemporaryDirectory() as spool:
        # the solo baseline FIRST: one session, fresh service, no
        # concurrency — the count every served lane must reproduce
        # bit-identically
        for arm in ("solo", "serve"):
            os.makedirs(os.path.join(spool, arm), exist_ok=True)
        solo_svc = CheckService(
            spool_dir=os.path.join(spool, "solo"), warm_start=False
        )
        solo = solo_svc.check(list(lane))
        if solo.state != "done":
            print(f"solo baseline failed: {solo.error}",
                  file=sys.stderr)
            return 1, None
        baseline_unique = solo.unique

        service = CheckService(
            spool_dir=os.path.join(spool, "serve")
        )
        server = service.http_server("127.0.0.1", 0)
        port = server.server_address[1]
        server_thread = threading.Thread(
            target=server.serve_forever, daemon=True
        )
        server_thread.start()
        scrape = None
        status_metrics = None
        try:
            phase_of = {}
            responses = []
            for name, clients in phases:
                print(f"  phase {name}: {clients} client(s) x "
                      f"'{' '.join(lane)}'")
                if name == "spike":
                    # mid-spike acceptance scrape: launch the fleet,
                    # read /.metrics + /.status while it runs
                    results = {}

                    def run(i):
                        results[i] = _post_check(port, lane)

                    threads = [
                        threading.Thread(target=run, args=(i,))
                        for i in range(clients)
                    ]
                    for t in threads:
                        t.start()
                    time.sleep(0.2)
                    scrape = _get(port, "/.metrics")
                    # status_block() — what /.status embeds as
                    # "service" when an Explorer is mounted
                    status_metrics = _json.loads(
                        _get(port, "/.serve/sessions")
                    ).get("metrics")
                    for t in threads:
                        t.join()
                    batch = [results[i] for i in range(clients)]
                else:
                    batch = _phase_fleet(port, lane, clients)
                for resp in batch:
                    responses.append(resp)
                    sid = (resp.get("session") or {}).get("session")
                    phase_of[sid] = name
        finally:
            server.shutdown()

        # -- every served lane bit-identical to the solo run --------
        for resp in responses:
            sess = resp.get("session") or {}
            if not resp.get("ok"):
                failures.append(
                    f"session {sess.get('session')} failed: "
                    f"{sess.get('error')}"
                )
            elif sess.get("unique") != baseline_unique:
                failures.append(
                    f"count divergence: session "
                    f"{sess.get('session')} unique="
                    f"{sess.get('unique')} vs solo "
                    f"{baseline_unique}"
                )
        if not failures:
            print(f"  counts: unique={baseline_unique:,} on every "
                  f"served session == the solo baseline")

        # -- the live scrape must serve the named families -----------
        missing = [f for f in REQUIRED_FAMILIES
                   if scrape is None or f not in scrape]
        if missing:
            failures.append(
                f"/.metrics scrape missing families: {missing}"
            )
        else:
            print(f"  /.metrics scrape: all "
                  f"{len(REQUIRED_FAMILIES)} required families live")
        if not isinstance(status_metrics, dict) or not {
            "active_sessions", "queue_depth", "refusals",
            "ttv_p99_sec",
        } <= set(status_metrics):
            failures.append(
                f"/.status metrics block incomplete: "
                f"{status_metrics}"
            )

        # -- per-phase percentiles, exact AND bucket-interpolated ----
        summary = serve_summary(service.events())
        ttv_of = {
            s["session"]: s.get("time_to_verdict_sec")
            for s in summary["sessions"]
        }
        phase_rows = []
        for name, clients in phases:
            ttvs = [v for sid, v in sorted(ttv_of.items())
                    if phase_of.get(sid) == name and v is not None]
            hist = Histogram("phase_ttv", "", threading.Lock())
            for v in ttvs:
                hist.observe(v)
            phase_rows.append(dict(
                phase=name,
                clients=clients,
                sessions=len(ttvs),
                ttv_p50_sec=quantile(ttvs, 0.50),
                ttv_p99_sec=quantile(ttvs, 0.99),
                ttv_p50_bucket_sec=hist.quantile(0.50),
                ttv_p99_bucket_sec=hist.quantile(0.99),
            ))
        print(f"  {'phase':<8s} {'n':>3s} {'p50':>10s} {'p99':>10s} "
              f"{'p50(bkt)':>10s} {'p99(bkt)':>10s}")
        for row in phase_rows:
            print(
                f"  {row['phase']:<8s} {row['sessions']:>3d} "
                f"{row['ttv_p50_sec']:>10.4f} "
                f"{row['ttv_p99_sec']:>10.4f} "
                f"{row['ttv_p50_bucket_sec']:>10.4f} "
                f"{row['ttv_p99_bucket_sec']:>10.4f}"
            )

        # -- the SLO gate -------------------------------------------
        families = service.metrics.snapshot()
        observed = slo_observed(families)
        evaluation = evaluate_slo(slo_spec, observed)
        for o in evaluation["objectives"]:
            print(
                f"  slo {o['objective']}: observed "
                f"{o['observed']}{o['unit']} {o['op']} "
                f"{o['threshold']}{o['unit']} -> {o['status']}"
            )
        print(f"  slo gate: {'OK' if evaluation['ok'] else 'FAILED'}")
        if not evaluation["ok"]:
            failures.append("SLO gate failed")

        doc = dict(
            lane=" ".join(lane),
            phases=phase_rows,
            solo_unique=baseline_unique,
            spec=slo_spec,
            observed=observed,
            evaluation=evaluation,
            status_metrics=status_metrics,
        )
        if json_out:
            jsonl, _chrome = service.write_trace(root=root)
            summary = dict(
                summary,
                trace=os.path.basename(jsonl),
                sustained=doc,
            )
            serve_path = write_serve_artifact(
                summary, root=root, metrics=families
            )
            slo_path = write_slo_artifact(
                dict(doc, serve_artifact=os.path.basename(serve_path),
                     trace=os.path.basename(jsonl)),
                root=root,
            )
            print(f"\nwrote {jsonl}\nwrote {serve_path}"
                  f"\nwrote {slo_path}")

    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    return (1 if failures else 0), doc


def main():
    ap = argparse.ArgumentParser(
        description="wave-batching A/B or sustained "
        "ramp->spike->drain SLO run against the resident checking "
        "service"
    )
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument(
        "--lane", default="2pc check-tpu 4",
        help='lane argv, e.g. "2pc check-tpu 4" (default)',
    )
    ap.add_argument(
        "--sustained", action="store_true",
        help="ramp->spike->drain against ONE live service over HTTP "
        "with the mid-spike /.metrics scrape and the SLO gate",
    )
    ap.add_argument("--ramp", type=int, default=2,
                    help="ramp-phase clients (sustained mode)")
    ap.add_argument("--spike", type=int, default=None,
                    help="spike-phase clients (default: --clients)")
    ap.add_argument("--drain", type=int, default=2,
                    help="drain-phase clients (sustained mode)")
    ap.add_argument("--slo-ttv-p50", type=float, default=None,
                    help="SLO: max p50 time-to-verdict (seconds)")
    ap.add_argument("--slo-ttv-p99", type=float, default=600.0,
                    help="SLO: max p99 time-to-verdict (seconds)")
    ap.add_argument("--slo-max-refusal-rate", type=float,
                    default=0.0, help="SLO: max admission refusal "
                    "rate (0..1)")
    ap.add_argument("--slo-max-queue-wait-p99", type=float,
                    default=600.0,
                    help="SLO: max p99 device-queue wait (seconds)")
    ap.add_argument("--slo-min-cache-hit-rate", type=float,
                    default=None,
                    help="SLO: min warm-start cache-hit rate (0..1)")
    ap.add_argument(
        "--json", action="store_true",
        help="export the TRACE_r* pair and write auto-numbered "
        "SERVE_r*.json (+ SLO_r*.json in sustained mode)",
    )
    ap.add_argument(
        "--root", default=None,
        help="artifact directory for --json (default: the repo root)",
    )
    args = ap.parse_args()
    lane = args.lane.split()

    if args.sustained:
        spec = dict(
            max_ttv_p50_sec=args.slo_ttv_p50,
            max_ttv_p99_sec=args.slo_ttv_p99,
            max_refusal_rate=args.slo_max_refusal_rate,
            max_queue_wait_p99_sec=args.slo_max_queue_wait_p99,
            min_cache_hit_rate=args.slo_min_cache_hit_rate,
        )
        spec = {k: v for k, v in spec.items() if v is not None}
        phases = [
            ("ramp", args.ramp),
            ("spike", args.spike or args.clients),
            ("drain", args.drain),
        ]
        print(
            f"serve loadtest (sustained): "
            f"{'/'.join(str(c) for _, c in phases)} clients "
            f"ramp/spike/drain x '{args.lane}'"
        )
        code, _doc = run_sustained(
            lane, phases, spec, json_out=args.json, root=args.root
        )
        return code

    from stateright_tpu.serve import (
        CheckService,
        serve_summary,
        write_serve_artifact,
    )

    print(
        f"serve loadtest: {args.clients} concurrent clients x "
        f"'{args.lane}' (batched vs fifo-serial)"
    )

    with tempfile.TemporaryDirectory() as spool:
        batched_svc = CheckService(
            spool_dir=os.path.join(spool, "batched"),
            warm_start=False,
            batch_sessions=args.clients,
            batch_window_sec=60.0,
        )
        batched_sessions = _run_fleet(batched_svc, lane, args.clients)
        fifo_svc = CheckService(
            spool_dir=os.path.join(spool, "fifo"),
            warm_start=False,
        )
        fifo_sessions = _run_fleet(fifo_svc, lane, args.clients)

        for arm, sessions in (("batched", batched_sessions),
                              ("fifo", fifo_sessions)):
            for s in sessions:
                if s.state != "done":
                    print(f"{arm} session {s.id} failed: {s.error}",
                          file=sys.stderr)
                    return 1
        counts = {s.unique for s in batched_sessions} | \
            {s.unique for s in fifo_sessions}
        if len(counts) != 1:
            print(f"count divergence across arms: {counts}",
                  file=sys.stderr)
            return 1

        summary = serve_summary(batched_svc.events())
        fifo_summary = serve_summary(fifo_svc.events())
        batched = _arm_stats(summary)
        fifo = _arm_stats(fifo_summary)
        amortization = (
            round(fifo["per_query_overhead_sec"]
                  / batched["per_query_overhead_sec"], 2)
            if batched["per_query_overhead_sec"] else None
        )

        print(f"  counts: unique={counts.pop():,} on every session, "
              "both arms")
        for label, arm in (("batched", batched),
                           ("fifo-serial", fifo)):
            print(
                f"  {label:<12s} per-query dispatch+sync "
                f"{arm['per_query_overhead_sec']:.4f} s | ttv p50 "
                f"{arm['ttv_p50_sec']:.4f} s p99 "
                f"{arm['ttv_p99_sec']:.4f} s"
            )
        print(f"  amortization: {amortization}x lower per-query "
              "overhead under the fused dispatch")

        summary["fifo_baseline"] = fifo
        summary["latency_quantiles"] = dict(
            batched={k: batched[k]
                     for k in ("ttv_p50_sec", "ttv_p99_sec")},
            fifo_serial={k: fifo[k]
                         for k in ("ttv_p50_sec", "ttv_p99_sec")},
        )
        summary["loadtest"] = dict(
            clients=args.clients,
            lane=args.lane,
            amortization_x=amortization,
            batched_per_query_overhead_sec=(
                batched["per_query_overhead_sec"]
            ),
            fifo_per_query_overhead_sec=(
                fifo["per_query_overhead_sec"]
            ),
        )
        if args.json:
            jsonl, _chrome = batched_svc.write_trace(root=args.root)
            summary["trace"] = os.path.basename(jsonl)
            path = write_serve_artifact(
                summary, root=args.root,
                metrics=batched_svc.metrics.snapshot(),
            )
            print(f"\nwrote {jsonl}\nwrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
