#!/usr/bin/env python
"""Latency report: the human-readable view of a run's wall-clock
telemetry — where the time went.

Reads a ``TRACE_r*.jsonl`` run-telemetry artifact whose run carries
the round-14 latency events (``program_build`` / ``verdict`` /
``latency_profile`` — any traced run on round >= 14 code) and renders
the three tables ROADMAP direction 4's latency-per-query story and
the pending BENCH_r06 warm/cold A/B read from:

* **compile-cache ledger** — every program build-or-fetch with its
  hit tier (in_process / disk / cold) and measured wall, so
  warm-vs-cold start attribution is exact per run (the cold wall is
  the number a resident service amortizes away),
* **dispatch / sync-floor split** — time-to-first-wave, the host
  dispatch wall vs the host-blocked-at-sync wall (the ~106 ms
  per-chunk floor of PERF.md §sync-floor) with shares of the run
  wall, and the compile attribution,
* **property verdict timeline** — per-property time-to-verdict:
  discovery vs exhaustion, settle wave/depth, wall since run start —
  plus the counterexample-reconstruction wall split (parent-log
  drain vs host decode) from the host-phase spans.

The derived summary comes from ``telemetry.latency_summary`` (the
same block bench lanes embed), so this report and those artifacts
cannot disagree. ``--json`` additionally writes an auto-numbered
``LAT_r*.json`` artifact (its own round sequence — ``LAT_r01`` first
— cross-referenced to the TRACE it was derived from; numbering via
stateright_tpu/artifacts.py).

Usage:
  python tools/latency_report.py TRACE_r20.jsonl
  python tools/latency_report.py TRACE_r20.jsonl --run 0
  python tools/latency_report.py TRACE_r20.jsonl --json

Exit status: 0 (report printed), 2 bad input / no latency events in
the trace (a pre-round-14 artifact).
"""

import argparse
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _sec(x) -> str:
    if x is None:
        return "-"
    return f"{x:,.4f} s" if x >= 0.001 else f"{x * 1e3:,.3f} ms"


def format_report(summary: dict) -> str:
    lines = [
        f"latency report: run #{summary['run']}, "
        f"engine {summary['engine']}",
    ]
    lane = summary.get("lane") or {}
    if lane:
        lines.append(
            "lane: " + ", ".join(
                f"{k}={lane[k]}" for k in sorted(lane)
            )
        )
    if summary.get("error"):
        lines.append(f"RUN ERROR: {summary['error']}")

    builds = summary.get("builds") or []
    if builds:
        lines.append("")
        lines.append("compile-cache ledger:")
        lines.append(
            f"  {'program':<16s} {'tier':<11s} {'key':<13s} "
            f"{'wall':>12s} {'cold':>12s}"
        )
        for b in builds:
            lines.append(
                f"  {b['program']:<16s} {b['tier']:<11s} "
                f"{(b.get('key') or '-'):<13s} "
                f"{_sec(b.get('wall_sec')):>12s} "
                f"{_sec(b.get('cold_sec')):>12s}"
            )

    prof = summary.get("profile")
    if prof:
        comp = prof.get("compile") or {}
        lines.append("")
        lines.append(
            f"wall split ({prof['chunks']} chunk(s), "
            f"{prof['waves']} waves, run wall "
            f"{_sec(prof['run_wall_sec'])}):"
        )
        if prof.get("resumed_from_wave") is not None:
            lines.append(
                f"  RESUMED from wave {prof['resumed_from_wave']}: "
                "walls cover the resumed half only (time to first "
                "wave = first wave AFTER the restore)"
            )

        def share(x):
            return f" ({x:.1%})" if x is not None else ""

        lines.append(
            f"  time to first wave:  "
            f"{_sec(prof['time_to_first_wave_sec'])}"
        )
        lines.append(
            f"  host dispatch:       {_sec(prof['dispatch_sec'])}"
            f"{share(prof.get('dispatch_share'))}"
            + (f"  [net of compile: "
               f"{_sec(prof['dispatch_net_sec'])}]"
               if prof.get("dispatch_net_sec")
               != prof.get("dispatch_sec") else "")
        )
        lines.append(
            f"  sync floor (fetch):  {_sec(prof['fetch_sec'])}"
            f"{share(prof.get('sync_share'))}  "
            f"[min/chunk {_sec(prof.get('fetch_min_sec'))}]"
        )
        if prof.get("device_sec") is not None:
            lines.append(
                f"  device wait (deep):  {_sec(prof['device_sec'])}"
                + (f" ({prof['overlap_share']:.1%} of chunk wall)"
                   if prof.get("overlap_share") is not None else "")
            )
        lines.append(
            f"  between chunks:      {_sec(prof['interchunk_sec'])}"
        )
        lines.append(
            f"  compile:             span {_sec(comp.get('span_sec'))}"
            f" + builds {_sec(comp.get('build_wall_sec'))}"
            f" (cold {_sec(comp.get('cold_sec'))})"
            f"{share(comp.get('share'))}"
            + (f"  tiers {comp['builds']}"
               if comp.get("builds") else "")
        )

    for w in summary.get("watchdogs") or ():
        lines.append(
            f"WATCHDOG TIMEOUT at chunk {w.get('chunk')}: deadline "
            f"{_sec(w.get('deadline_sec'))} "
            f"(rolling max chunk wall "
            f"{_sec(w.get('rolling_max_chunk_sec'))}, waited "
            f"{_sec(w.get('waited_sec'))}) — a hung dispatch; the "
            "latency attribution rides the event"
        )
    for d in summary.get("degrades") or ():
        lines.append(
            f"DEGRADED at wave {d.get('wave')}: "
            f"S={d.get('from_shards')} -> S={d.get('to_shards')} "
            f"({d.get('reason')}, {d.get('rerouted_rows')} rows "
            "re-routed from the snapshot)"
        )

    verdicts = summary.get("verdicts") or []
    if verdicts:
        lines.append("")
        lines.append("time to verdict:")
        lines.append(
            f"  {'property':<28s} {'expectation':<12s} "
            f"{'settled':<11s} {'wave':>5s} {'depth':>5s} "
            f"{'wall':>12s}"
        )
        for v in verdicts:
            lines.append(
                f"  {v['property']:<28s} {v['expectation']:<12s} "
                f"{v['kind']:<11s} "
                f"{v['wave'] if v.get('wave') is not None else '-':>5} "
                f"{v['depth'] if v.get('depth') is not None else '-':>5} "
                f"{_sec(v['t_since_run']):>12s}"
            )

    phases = summary.get("phases") or {}
    cex = {k: v for k, v in phases.items()
           if k.startswith("cex_")
           or k == "counterexample_reconstruction"}
    if cex:
        lines.append(
            "counterexample reconstruction: "
            + ", ".join(
                f"{k.replace('cex_', '')} {_sec(v)}"
                for k, v in sorted(cex.items())
            )
        )
    if "property_check" in phases:
        lines.append(
            f"host property checks: {_sec(phases['property_check'])}"
        )
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(
        description="compile-ledger / sync-floor / time-to-verdict "
        "report over a TRACE"
    )
    ap.add_argument("trace", help="TRACE_r*.jsonl artifact")
    ap.add_argument(
        "--run", type=int, default=None,
        help="run index inside the trace (default: the last run)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="also write an auto-numbered LAT_r*.json artifact "
        "(beside the trace's repo artifacts)",
    )
    ap.add_argument(
        "--root", default=None,
        help="artifact directory for --json (default: the repo root)",
    )
    args = ap.parse_args()

    from stateright_tpu.telemetry import (
        latency_summary,
        load_trace,
        validate_events,
        write_latency_artifact,
    )

    try:
        events = load_trace(args.trace)
        validate_events(events)
    except (OSError, ValueError) as exc:
        print(f"latency_report: bad input: {exc}", file=sys.stderr)
        sys.exit(2)

    runs = sorted({e["run"] for e in events
                   if e["ev"] == "run_begin"})
    if args.run is not None and args.run not in runs:
        print(
            f"latency_report: run {args.run} not in this trace "
            f"(runs: {runs})",
            file=sys.stderr,
        )
        sys.exit(2)

    summary = latency_summary(events, run=args.run)
    if summary is None:
        print(
            "latency_report: no latency events in this trace — trace "
            "a run on round >= 14 code "
            "(program_build/verdict/latency_profile land "
            "automatically on traced runs)",
            file=sys.stderr,
        )
        sys.exit(2)
    print(format_report(summary))
    if args.json:
        summary = dict(summary, trace=os.path.basename(args.trace))
        path = write_latency_artifact(summary, root=args.root)
        print(f"\nwrote {path}")


if __name__ == "__main__":
    main()
