#!/usr/bin/env python
"""Comms-lint CLI: pin the mesh communication contract, on CPU.

Runs the comms rule family (stateright_tpu/analysis/comms.py —
``no-collective-in-switch``, ``no-unsorted-all-to-all``,
``scalar-only-reductions``, ``no-all-gather``, the gated
``comms-bytes`` budget) over BOTH sharded engines' full wave bodies
(sort-merge + hash, traced and untraced forms, real S=2 mesh), the
rm=5/S=8 reconciliation fixture at the committed TRACE_r16 dryrun
config, and every registry encoding's ``engine:sharded`` pair
pipeline. Exit status 0 iff clean — the same gate ``pytest -m lint``
runs in tier-1 (tests/test_comms_lint.py).

Usage:
  python tools/lint_comms.py                 # human report, exit != 0 on findings
  python tools/lint_comms.py --json          # also write COMM_r*.json
  python tools/lint_comms.py --json out.json
  python tools/lint_comms.py --no-wave-body  # registry encodings only
  python tools/lint_comms.py --hlo           # compile each wave body and
                                             # reconcile the module's
                                             # collective ops vs the jaxpr
                                             # estimate (slower)

``--json`` artifacts number in their OWN ``COMM_r*`` sequence (like
MEM; stateright_tpu/artifacts.py): a COMM artifact is the static
communication contract at one commit — bench.py and lint_kernels.py
cross-reference the newest one by name (artifacts.latest_comms_summary)
instead of sharing the BENCH/LINT round counter.
"""

import argparse
import json
import os
import sys

# The reconciliation fixture needs an 8-device mesh; claim the virtual
# CPU devices BEFORE jax initializes a backend (no-op when the caller
# already set a count).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main():
    ap = argparse.ArgumentParser(
        description="static comms-lint over the sharded wave paths"
    )
    ap.add_argument(
        "--json", nargs="?", const="auto", default=None,
        metavar="PATH",
        help="write the report as JSON (default: auto-numbered "
        "COMM_r*.json in the repo root)",
    )
    ap.add_argument(
        "--no-wave-body", action="store_true",
        help="skip the engine wave-body fixtures (registry encodings "
        "only)",
    )
    ap.add_argument(
        "--no-reconciliation", action="store_true",
        help="skip the rm=5/S=8 TRACE_r16-config fixture",
    )
    ap.add_argument(
        "--hlo", action="store_true",
        help="also compile each wave-body fixture and reconcile the "
        "optimized module's collective ops against the jaxpr "
        "estimate (slower: compiles the full wave bodies)",
    )
    args = ap.parse_args()
    if args.hlo and args.no_wave_body:
        # the HLO cross-check compiles the wave-body fixtures; with
        # them skipped there is nothing to reconcile — exiting 0 as
        # if the check ran would be a silent pass
        ap.error("--hlo requires the wave-body fixtures "
                 "(drop --no-wave-body)")

    import jax

    jax.config.update("jax_platforms", "cpu")

    from stateright_tpu.analysis.comms import (
        format_comms_report,
        hlo_collective_crosscheck,
        run_comms_lint,
    )

    # the gate traces each wave-body fixture once; --hlo reuses the
    # same fixture objects (fn + carry shapes) instead of rebuilding
    # the sharded engines and re-tracing
    fixtures: list = []
    report = run_comms_lint(
        wave_bodies=not args.no_wave_body,
        reconciliation=not args.no_reconciliation,
        fixtures_out=fixtures,
    )

    if args.hlo and not args.no_wave_body:
        hlo_block = {}
        for fixture in fixtures:
            jaxpr_cats = report["comms"][fixture["name"]].get(
                "per_category", {}
            )
            xc = hlo_collective_crosscheck(fixture, jaxpr_cats)
            hlo_block[fixture["name"]] = dict(
                hlo=xc["hlo"],
                jaxpr=xc["jaxpr"],
                byte_ratio=xc["byte_ratio"],
            )
            report["findings"].extend(
                f.as_dict() for f in xc["findings"]
            )
            if any(f.severity == "error" for f in xc["findings"]):
                report["clean"] = False
        report["hlo"] = hlo_block

    print(format_comms_report(report))
    if args.hlo and "hlo" in report:
        print("hlo collective reconciliation (ops jaxpr->hlo, "
              "byte ratio):")
        for name, h in report["hlo"].items():
            for cat in sorted(set(h["jaxpr"]) | set(h["hlo"])):
                j = h["jaxpr"].get(cat, {"eqns": 0})
                c = h["hlo"].get(cat, {"ops": 0})
                r = h["byte_ratio"].get(cat)
                print(f"  {name:44s} {cat:12s} "
                      f"{j['eqns']:3d} -> {c['ops']:3d}"
                      + (f"  x{r}" if r is not None else ""))

    if args.json is not None:
        from stateright_tpu.artifacts import (
            artifact_path,
            next_round,
            provenance,
            repo_root,
        )

        report["provenance"] = provenance(
            lane=dict(
                wave_bodies=not args.no_wave_body,
                reconciliation=not args.no_reconciliation,
                hlo=args.hlo,
            )
        )
        if args.json == "auto":
            root = repo_root()
            path = artifact_path(
                "COMM", "json", root=root,
                round=next_round(root, stems=("COMM",)),
            )
        else:
            path = args.json
        with open(path, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {path}")

    sys.exit(0 if report["clean"] else 1)


if __name__ == "__main__":
    main()
