#!/usr/bin/env python
"""SLO report: the exit-code gate over a metrics rollup or a live
``/.metrics`` endpoint — the hands-off half of ROADMAP direction
2(c)'s elasticity story (scripts and CI act on the exit code; an
autoscaler would act on the same observed values).

Evaluates a declarative SLO spec (stateright_tpu/metrics.py
``SLO_OBJECTIVES``: max p50/p99 time-to-verdict, max admission
refusal rate, max p99 queue wait, min warm-start cache-hit rate)
against EITHER:

* ``--rollup FILE`` — a ``--metrics-interval`` JSONL rollup (the last
  ``metrics_rollup`` event, schema-validated through telemetry's
  validator like every other event stream), or
* ``--url URL`` — a live endpoint: scrapes ``GET /.metrics`` once and
  parses the Prometheus text back into snapshot families
  (``parse_prometheus`` — the exposition round-trips, pinned by the
  metrics tests).

The spec comes from ``--spec FILE`` (a JSON object of
``SLO_OBJECTIVES`` keys) or the individual ``--max-*`` / ``--min-*``
flags; flags override the file. An objective whose signal is absent
from the families evaluates UNMEASURED and fails the gate — silence
is never compliance.

``--json`` writes an auto-numbered ``SLO_r*.json`` artifact (its own
round sequence, SLO_r01 first; numbering + provenance via
stateright_tpu/artifacts.py) that bench provenance then embeds via
``artifacts.latest_slo_summary``.

Usage:
  python tools/slo_report.py --rollup stateright_tpu.metrics.jsonl \\
      --max-ttv-p99 30 --max-refusal-rate 0.05
  python tools/slo_report.py --url http://127.0.0.1:8080 \\
      --spec slo.json --json

Exit status: 0 all objectives met, 1 any objective violated or
unmeasured, 2 bad input (unreadable rollup/endpoint, no rollup event,
empty spec, unknown spec key).
"""

import argparse
import json
import os
import sys
import urllib.error
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

#: flag name -> SLO_OBJECTIVES spec key
_FLAG_OBJECTIVES = {
    "max_ttv_p50": "max_ttv_p50_sec",
    "max_ttv_p99": "max_ttv_p99_sec",
    "max_refusal_rate": "max_refusal_rate",
    "max_queue_wait_p99": "max_queue_wait_p99_sec",
    "min_cache_hit_rate": "min_cache_hit_rate",
}


def _load_families(args):
    """The observed side: snapshot families from the rollup file or
    one live scrape. Raises ValueError on bad input."""
    if args.rollup is not None:
        from stateright_tpu.metrics import load_rollup

        try:
            return load_rollup(args.rollup)["families"], args.rollup
        except OSError as exc:
            raise ValueError(f"cannot read rollup: {exc}")
    from stateright_tpu.metrics import parse_prometheus

    url = args.url.rstrip("/")
    if not url.endswith("/.metrics"):
        url += "/.metrics"
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            text = r.read().decode()
    except (urllib.error.URLError, OSError) as exc:
        raise ValueError(f"cannot scrape {url}: {exc}")
    return parse_prometheus(text), url


def main():
    ap = argparse.ArgumentParser(
        description="evaluate a declarative SLO spec against a "
        "metrics rollup or a live /.metrics endpoint; the exit code "
        "is the gate"
    )
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--rollup", default=None,
                     help="metrics rollup JSONL (--metrics-interval "
                     "output); the LAST rollup event is evaluated")
    src.add_argument("--url", default=None,
                     help="live endpoint base URL or full /.metrics "
                     "URL to scrape once")
    ap.add_argument("--spec", default=None,
                    help="JSON file of SLO objectives "
                    "(stateright_tpu/metrics.py SLO_OBJECTIVES keys)")
    ap.add_argument("--max-ttv-p50", type=float, default=None,
                    help="max p50 time-to-verdict (seconds)")
    ap.add_argument("--max-ttv-p99", type=float, default=None,
                    help="max p99 time-to-verdict (seconds)")
    ap.add_argument("--max-refusal-rate", type=float, default=None,
                    help="max admission refusal rate (0..1)")
    ap.add_argument("--max-queue-wait-p99", type=float, default=None,
                    help="max p99 device-queue wait (seconds)")
    ap.add_argument("--min-cache-hit-rate", type=float, default=None,
                    help="min warm-start cache-hit rate (0..1)")
    ap.add_argument(
        "--json", action="store_true",
        help="also write an auto-numbered SLO_r*.json artifact",
    )
    ap.add_argument(
        "--root", default=None,
        help="artifact directory for --json (default: the repo root)",
    )
    args = ap.parse_args()

    from stateright_tpu.metrics import (
        evaluate_slo,
        slo_observed,
        write_slo_artifact,
    )

    spec = {}
    if args.spec is not None:
        try:
            with open(args.spec) as f:
                loaded = json.load(f)
        except (OSError, ValueError) as exc:
            print(f"slo_report: bad --spec: {exc}", file=sys.stderr)
            return 2
        if not isinstance(loaded, dict):
            print("slo_report: --spec must be a JSON object",
                  file=sys.stderr)
            return 2
        spec.update(loaded)
    for flag, key in _FLAG_OBJECTIVES.items():
        v = getattr(args, flag)
        if v is not None:
            spec[key] = v
    if not spec:
        print(
            "slo_report: empty spec — pass --spec FILE or at least "
            "one objective flag "
            "(--max-ttv-p50/--max-ttv-p99/--max-refusal-rate/"
            "--max-queue-wait-p99/--min-cache-hit-rate)",
            file=sys.stderr,
        )
        return 2

    try:
        families, source = _load_families(args)
        observed = slo_observed(families)
        evaluation = evaluate_slo(spec, observed)
    except ValueError as exc:
        print(f"slo_report: bad input: {exc}", file=sys.stderr)
        return 2

    print(f"slo report: {source}")
    print(f"  {'objective':<26s} {'threshold':>12s} "
          f"{'observed':>12s} {'status':<10s}")
    for o in evaluation["objectives"]:
        obs = ("-" if o["observed"] is None
               else f"{o['observed']:g}{o['unit']}")
        thr = f"{o['op']} {o['threshold']:g}{o['unit']}"
        print(
            f"  {o['objective']:<26s} {thr:>12s} "
            f"{obs:>12s} {o['status'].upper():<10s}"
        )
    print(f"  gate: {'OK' if evaluation['ok'] else 'FAILED'}")

    if args.json:
        path = write_slo_artifact(
            dict(
                source=os.path.basename(source)
                if args.rollup else source,
                spec=spec,
                observed=observed,
                evaluation=evaluation,
            ),
            root=args.root,
        )
        print(f"\nwrote {path}")
    return 0 if evaluation["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
