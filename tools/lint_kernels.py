#!/usr/bin/env python
"""Kernel-lint CLI: pin the sparse-engine codegen contract, on CPU.

Runs the static-analysis rule registry (stateright_tpu/analysis/) over
every registered encoding (hand paxos, hand 2pc, compiled ABD ordered,
compiled ping-pong) × both sparse engine pipelines (the single-chip
and sharded invocations of ``sparse_pair_candidates``), plus the
engine wave-body fixture for the branch-shape rule and the
carry-copy-bytes estimator. Exit status 0 iff clean — the same gate
``pytest -m lint`` runs in tier-1.

Usage:
  python tools/lint_kernels.py                # human report, exit != 0 on findings
  python tools/lint_kernels.py --json         # also write LINT_r*.json
  python tools/lint_kernels.py --json out.json
  python tools/lint_kernels.py --encoding hand-2pc-rm4
  python tools/lint_kernels.py --no-wave-body # skip the fixture trace
  python tools/lint_kernels.py --hlo          # add compiled-HLO category
                                              # pricing per engine path
                                              # (slower: compiles on CPU)

The ``--json`` artifact lands alongside the BENCH_r*.json artifacts,
auto-numbered past the highest existing round of ANY artifact family
(the shared helper in stateright_tpu/artifacts.py — the same one the
telemetry TRACE exporter uses) so a perf round can point at "lint
clean at r07" the way it points at its bench lane; the artifact embeds
the standard provenance block (jax/jaxlib, device, git SHA).
"""

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _hlo_pricing(encodings) -> dict:
    """Optional --hlo pass: compile each encoding's engine pipeline on
    the current backend and price the wall categories (the HLO-level
    counterpart of the jaxpr carry-copy-bytes estimate), via the same
    shared tables the wave-wall profiler reports with."""
    import jax
    import jax.numpy as jnp

    from stateright_tpu.analysis import (
        HLO_WALL_CATEGORIES,
        parse_hlo_categories,
    )
    from stateright_tpu.analysis.lint import (
        LINT_N,
        engine_pipe_params,
        engine_trace_operands,
    )
    from stateright_tpu.checkers.tpu_sortmerge import (
        sparse_pair_candidates,
    )

    out = {}
    n = LINT_N
    for spec in encodings:
        enc = spec.factory()
        # the SAME invocation recipes the jaxpr rules audited
        # (engine_pipe_params, BOTH pipeline shapes) — the --hlo pass
        # must price the programs the lint traced, not a private
        # variant.
        for compact in (False, True):
            params = engine_pipe_params(enc, n, compact)
            # the [W, N] resident layout (registry.ENGINE_LAYOUT):
            # full carry buffer + n_rows, same as the jaxpr traces
            frontier, fval, n_rows = engine_trace_operands(enc, n)

            def pipe(frontier_t, fval):
                return sparse_pair_candidates(
                    enc, frontier_t, fval, jnp.bool_(True),
                    n_rows=n_rows, **params,
                )

            hlo = (
                jax.jit(pipe)
                .lower(frontier, fval)
                .compile()
                .as_text()
            )
            cats = parse_hlo_categories(hlo)
            wall = sum(
                s["bytes"] for c, s in cats.items()
                if c in HLO_WALL_CATEGORIES
            )
            key = spec.name + ("+compact" if compact else "")
            out[key] = {
                "categories": cats,
                "wall_bytes": wall,
            }
    return out


def main():
    ap = argparse.ArgumentParser(
        description="static kernel-lint over the sparse-engine "
        "codegen contract"
    )
    ap.add_argument(
        "--json", nargs="?", const="auto", default=None,
        metavar="PATH",
        help="write the report as JSON (default: auto-numbered "
        "LINT_r*.json in the repo root)",
    )
    ap.add_argument(
        "--encoding", action="append", default=None,
        help="lint only this registered encoding (repeatable)",
    )
    ap.add_argument(
        "--engines", default="single,sharded",
        help="comma-separated engine pipelines (default both)",
    )
    ap.add_argument(
        "--no-wave-body", action="store_true",
        help="skip the engine wave-body fixture trace",
    )
    ap.add_argument(
        "--hlo", action="store_true",
        help="also compile each engine pipeline and price the HLO "
        "wall categories (slower)",
    )
    args = ap.parse_args()

    from stateright_tpu.analysis import (
        ENCODINGS,
        format_report,
        get_encoding_spec,
        run_lint,
    )

    if args.encoding:
        specs = tuple(get_encoding_spec(n) for n in args.encoding)
    else:
        specs = ENCODINGS

    report = run_lint(
        encodings=specs,
        engines=tuple(args.engines.split(",")),
        wave_body=not args.no_wave_body,
    )
    if args.hlo:
        report["hlo"] = _hlo_pricing(specs)

    print(format_report(report))
    if args.hlo:
        print("hlo wall-category bytes (engine pipeline, compiled):")
        for name, h in report["hlo"].items():
            print(f"  {name:36s} {h['wall_bytes'] / 1e6:9.2f} MB")

    if args.json is not None:
        from stateright_tpu.artifacts import (
            artifact_path,
            latest_comms_summary,
            provenance,
        )

        report["provenance"] = provenance(
            lane=dict(
                encodings=[s.name for s in specs],
                engines=args.engines.split(","),
                wave_body=not args.no_wave_body,
                hlo=args.hlo,
            )
        )
        # the newest comms-lint artifact, by name (round 13): a LINT
        # round and the communication contract it was measured beside
        # pair up without hand-matching. Best effort — None when no
        # COMM artifact exists yet.
        comms_ref = latest_comms_summary()
        if comms_ref is not None:
            report["provenance"]["comms"] = {
                "artifact": comms_ref["artifact"],
                "clean": comms_ref["clean"],
            }
        path = (
            artifact_path("LINT", "json")
            if args.json == "auto"
            else args.json
        )
        with open(path, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {path}")

    sys.exit(0 if report["clean"] else 1)


if __name__ == "__main__":
    main()
