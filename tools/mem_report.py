#!/usr/bin/env python
"""Memory report: the human-readable view of a run's memory telemetry.

Reads a ``TRACE_r*.jsonl`` run-telemetry artifact whose run carries
the round-12 memory events (``memory_plan`` / ``memory_watermark`` /
per-chunk ``mem_bytes`` — any traced run of the device engines) and
renders the capacity numbers ROADMAP directions 1b (tiered visited
set) and 2b (HBM-staged merge) decide from:

* **resident-buffer ledger** — every chunk-carry buffer the engine
  keeps device-resident between syncs (frontier, vkeys, plog, ebits,
  the wave/shard logs), with dtype/shape/bytes and per-shard splits
  on mesh runs,
* **per-ladder-class staging** — what each (f, v) class's wave
  buffers cost, so the plan is a function of the class the adaptive
  ladder dispatches, not just the peak (CHUNKED memory-lean classes
  are flagged),
* **compiled-program analysis** — XLA's own
  ``Compiled.memory_analysis()`` of the wave program (temp/argument/
  output/alias bytes; '-' where the backend doesn't report it),
* **live watermarks** — the per-chunk device bytes-in-use trajectory
  and the run peak, plus observed-vs-capacity headroom (joined from
  the persisted auto-budget store) and the **capacity projection**:
  predicted bytes at the next visited ladder class — the number that
  decides when V stops fitting VMEM.

The derived summary comes from ``telemetry.memory_summary`` (the same
block bench lanes and the MULTICHIP dryrun embed), so this report and
those artifacts cannot disagree. ``--json`` additionally writes an
auto-numbered ``MEM_r*.json`` artifact (its own round sequence —
``MEM_r01`` first — cross-referenced to the TRACE it was derived
from; numbering via stateright_tpu/artifacts.py).

Usage:
  python tools/mem_report.py TRACE_r18.jsonl
  python tools/mem_report.py TRACE_r18.jsonl --run 0
  python tools/mem_report.py TRACE_r18.jsonl --json

Exit status: 0 (report printed), 2 bad input / no memory events in
the trace (a pre-round-12 artifact, or an untraced-engine run).
"""

import argparse
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def format_report(summary: dict, max_chunks: int = 20) -> str:
    from stateright_tpu.memplan import format_bytes as fb

    lines = [
        f"memory report: run #{summary['run']}, "
        f"engine {summary['engine']}",
    ]
    lane = summary.get("lane") or {}
    if lane:
        lines.append(
            "lane: " + ", ".join(
                f"{k}={lane[k]}" for k in sorted(lane)
            )
        )
    plan = summary.get("plan")
    if plan:
        lines.append("")
        lines.append(
            f"resident-buffer ledger ({plan['n_shards']} shard(s), "
            f"total {fb(plan['resident_bytes'])}):"
        )
        lines.append(
            f"  {'buffer':14s} {'shape':>18s} {'dtype':>8s} "
            f"{'bytes':>14s}" + (
                f" {'per-shard':>12s}" if plan["n_shards"] > 1 else ""
            )
        )
        for e in plan["resident"]:
            shape = "x".join(map(str, e["shape"])) or "scalar"
            row = (
                f"  {e['name']:14s} {shape:>18s} {e['dtype']:>8s} "
                f"{e['bytes']:>14,d}"
            )
            if plan["n_shards"] > 1:
                row += f" {e.get('per_shard_bytes', e['bytes']):>12,d}"
            lines.append(row)
        if plan.get("classes"):
            lines.append("")
            lines.append("per-ladder-class staging (per shard):")
            lines.append(
                f"  {'f':>2s} {'mode':12s} {'frontier':>9s} "
                f"{'buffer':>9s} {'tiles':>6s} {'bytes':>14s}"
            )
            for c in plan["classes"]:
                lines.append(
                    f"  {c['f_class']:2d} {c['mode']:12s} "
                    f"{c['frontier_rows']:9,d} "
                    f"{c.get('buffer_rows', 0):9,d} "
                    f"{c.get('tiles', 1):6d} "
                    f"{c['staging_bytes']:>14,d}"
                )
        if plan.get("v_classes"):
            lines.append("  v-ladder merge scratch: " + ", ".join(
                f"v{v['v_class']}={v['visited_rows']:,}rows/"
                f"{fb(v['merge_scratch_bytes'])}"
                for v in plan["v_classes"]
            ))
        lines.append(
            f"plan total (resident + peak-class staging): "
            f"{fb(plan['total_bytes'])}"
        )
        comp = plan.get("compiled")
        lines.append("")
        if comp:
            lines.append(
                "compiled wave program (XLA memory_analysis): "
                f"temp {fb(comp.get('temp_size_in_bytes'))}, "
                f"args {fb(comp.get('argument_size_in_bytes'))}, "
                f"out {fb(comp.get('output_size_in_bytes'))}, "
                f"alias {fb(comp.get('alias_size_in_bytes'))}"
            )
        else:
            lines.append(
                "compiled wave program: memory_analysis not reported "
                "by this backend"
            )
    for m in summary.get("engine_modes") or ():
        lines.append(
            f"ENGINE MODE: {m.get('engine')} f_class "
            f"{m.get('f_class')} ran {m.get('mode').upper()} "
            f"memory-lean ({m.get('buffer_rows'):,} rows in "
            f"{m.get('chunks')} chunks of {m.get('chunk_rows'):,}; "
            f"flat budget {fb(m.get('flat_budget_bytes'))})"
        )
    spills = summary.get("tier_spills") or []
    if spills:
        lines.append("")
        lines.append(
            f"tier spills ({len(spills)} — hot prefix -> host-DRAM "
            "cold runs at the chunk sync):"
        )
        shown = spills[:max_chunks]
        lines.append(
            "  rows per spill: " + " ".join(
                f"{s['rows']:,}" for s in shown
            ) + (f" ... ({len(spills) - max_chunks} more)"
                 if len(spills) > max_chunks else "")
        )
        last = spills[-1]
        lines.append(
            f"  cold tier after last spill: "
            f"{last['cold_rows_total']:,} rows = "
            f"{fb(last['cold_bytes_total'])}"
        )
    wm = summary.get("watermark")
    chunks = summary.get("chunk_mem") or []
    if wm or chunks:
        lines.append("")
        lines.append("live watermarks:")
    if chunks:
        shown = chunks[:max_chunks]
        lines.append(
            "  per-chunk bytes-in-use: " + " ".join(
                fb(c["bytes"]) for c in shown
            ) + (f" ... ({len(chunks) - max_chunks} more)"
                 if len(chunks) > max_chunks else "")
        )
    if wm:
        lines.append(
            f"  run peak: {fb(wm.get('device_peak_bytes'))} "
            f"(source: {wm.get('source')}, "
            f"{wm.get('polls', 0)} polls)"
        )
        hr = wm.get("headroom") or {}
        occ = hr.get("occupancy")
        lines.append(
            f"  visited headroom: {hr.get('visited_rows', 0):,}/"
            f"{hr.get('visited_capacity', 0):,} rows"
            + (f" ({occ:.1%})" if occ is not None else "")
            + f" = {fb(hr.get('visited_used_bytes'))} of "
            f"{fb(hr.get('visited_capacity_bytes'))}"
        )
        budget = hr.get("budget")
        if budget:
            ratio = budget.get("headroom_ratio")
            lines.append(
                f"  auto-budget: cand_capacity "
                f"{budget.get('cand_capacity'):,} vs observed peak "
                f"{budget.get('observed_peak') or 0:,}"
                + (f" ({ratio:.2f}x headroom)"
                   if ratio is not None else "")
            )
        tier = (hr.get("tier") or {}) if hr else {}
        if tier:
            hot = tier.get("hot_ceiling_rows")
            cold_rows = tier.get("cold_rows_total", 0)
            hot_rows = hr.get("visited_rows", 0) - cold_rows
            lines.append(
                f"  tiered visited set: hot {hot_rows:,} rows "
                f"(device, ceiling "
                + (f"{hot:,}" if hot is not None else "-")
                + f") / cold {cold_rows:,} rows = "
                f"{fb(tier.get('cold_bytes_total'))} in "
                f"{tier.get('runs', 0)} host-DRAM run(s), "
                f"{tier.get('spills', 0)} spill(s) "
                f"(spill wall {tier.get('spill_wall_sec', 0):.3f}s, "
                f"worker ingest {tier.get('ingest_sec', 0):.3f}s "
                "overlapped)"
            )
        proj = wm.get("projection") or {}
        if proj.get("kind") == "next_v_class":
            lines.append(
                f"  projection (next v-class): "
                f"{proj.get('current_rows', 0):,} -> "
                f"{proj.get('next_rows', 0):,} visited rows = "
                f"{fb(proj.get('next_vkeys_bytes'))} resident vkeys "
                f"+ {fb(proj.get('next_merge_scratch_bytes'))} merge "
                "scratch"
            )
        elif proj:
            lines.append(
                f"  projection ({proj.get('kind')}): "
                f"{proj.get('next_rows', 0):,} rows = "
                f"{fb(proj.get('next_visited_bytes'))}"
            )
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(
        description="memory plan/watermark/headroom report over a "
        "TRACE"
    )
    ap.add_argument("trace", help="TRACE_r*.jsonl artifact")
    ap.add_argument(
        "--run", type=int, default=None,
        help="run index inside the trace (default: the last run)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="also write an auto-numbered MEM_r*.json artifact "
        "(beside the trace's repo artifacts)",
    )
    ap.add_argument(
        "--root", default=None,
        help="artifact directory for --json (default: the repo root)",
    )
    ap.add_argument(
        "--chunks", type=int, default=20,
        help="max per-chunk watermark samples to print (default 20)",
    )
    args = ap.parse_args()

    from stateright_tpu.telemetry import (
        load_trace,
        memory_summary,
        validate_events,
    )

    try:
        events = load_trace(args.trace)
        validate_events(events)
    except (OSError, ValueError) as exc:
        print(f"mem_report: bad input: {exc}", file=sys.stderr)
        sys.exit(2)

    runs = sorted({e["run"] for e in events
                   if e["ev"] == "run_begin"})
    if args.run is not None and args.run not in runs:
        print(
            f"mem_report: run {args.run} not in this trace "
            f"(runs: {runs})",
            file=sys.stderr,
        )
        sys.exit(2)

    summary = memory_summary(events, run=args.run)
    if summary is None:
        print(
            "mem_report: no memory events in this trace — trace a "
            "device-engine run on round >= 12 code "
            "(memory_plan/memory_watermark land automatically on "
            "traced runs)",
            file=sys.stderr,
        )
        sys.exit(2)
    print(format_report(summary, args.chunks))
    if args.json:
        from stateright_tpu.memplan import write_memory_artifact

        summary = dict(summary, trace=os.path.basename(args.trace))
        path = write_memory_artifact(summary, root=args.root)
        print(f"\nwrote {path}")


if __name__ == "__main__":
    main()
