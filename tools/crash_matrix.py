#!/usr/bin/env python
"""Crash matrix: the end-to-end proof that checkpoint/resume recovers.

Runs every cell of the fault-injection matrix
(stateright_tpu/faultinject.py) against one workload and verdicts each
as **recovered** (kill or device fault → resumed/retried to the exact
baseline count), **refused** (torn snapshot, stale manifest → the
named Snapshot* error), or **continue-degraded** (a persistent
per-shard fault → the supervisor dropped the shard, re-sharded the
last snapshot onto the survivors, and the degraded run completed to
the identical count) — the contract is
continue-degraded-or-recover-or-refuse-loudly, never a silent wrong
answer and never a hang:

* ``kill`` — a SUBPROCESS runs the real CLI check lane with
  ``--checkpoint-every`` and an armed ``STPU_FAULTS`` process kill at
  a seeded chunk boundary (``os._exit(137)``, no cleanup — a real
  preemption), then a second subprocess ``--resume``\\ s from the
  snapshot; the resumed run's final count must equal the baseline's;
* ``device_fault`` — in-process: an injected mid-chunk exception under
  supervision (checkpoint.supervised_run) must self-recover from the
  last snapshot to the identical count in ONE join;
* ``torn_truncate`` / ``torn_flip`` — a valid snapshot damaged on disk
  must be detected (``SnapshotCorruptError``) at resume;
* ``stale_sha`` / ``stale_encoding`` — a rewritten manifest must be
  refused (``SnapshotStaleError``) at resume;
* ``shard_fault_degrade`` (degrade-and-continue round) — a PERSISTENT
  per-shard device fault on an S=2 virtual mesh under
  ``degrade_on_fault``: the FailurePolicy classifies the repeat
  offender, drops the shard, re-shards the snapshot onto the
  survivor, and the run must complete to the identical count
  (**continue-degraded**);
* ``collective_raise`` — an injected raise at the mesh collective
  seam under supervision must recover like any chunk fault;
* ``hang_watchdog`` — an injected chunk-dispatch hang (the livelock
  shape: a sleep, no exception) under an armed watchdog: the breach
  must be DETECTED within the derived deadline, and the run either
  recovers from the snapshot (**recovered**) or raises the
  WatchdogTimeout with its attribution (**refused** — loudly, never
  a hang).

``--mesh-degrade`` additionally runs the flagship acceptance pair: a
TRACED 8-shard 2pc rm=5 mesh run with a persistent shard fault at a
mid-run chunk must automatically degrade and complete to the
identical 8,832, with the resume/degrade-aware
``tools/trace_diff.py`` alignment reporting ZERO global-counter
divergence vs the uninterrupted traced baseline; the TRACE pair and
the diff verdict are embedded in the artifact.

``--trace`` additionally runs the baseline and the resumed half
traced (``TRACE_r*`` artifacts land in the repo root) and embeds the
``tools/trace_diff.py`` verdict: the resumed run's wave stream must
align with the uninterrupted baseline at ZERO counter divergence
(telemetry's resume-aware alignment — pre-kill waves died with the
killed process, the overlap must match exactly).

``--json`` writes an auto-numbered ``CKPT_r*.json`` artifact (its own
round sequence like MEM/LAT/COMM, via stateright_tpu/artifacts.py)
carrying the per-cell verdicts, the snapshot byte size vs the memory
ledger's predicted resident bytes, and the trace-diff block.
bench.py embeds the newest CKPT artifact beside LINT/COMM
(``artifacts.latest_ckpt_summary``).

Usage:
  python tools/crash_matrix.py                       # 2pc rm=4, fast
  python tools/crash_matrix.py --workload paxos --count 4 --trace --json
  python tools/crash_matrix.py --seed 7 --json

Exit status: 0 all cells recover-or-refuse, 1 any cell failed,
2 bad input.
"""

import argparse
import glob
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_DONE_RE = re.compile(r"Done\. states=(\d+), unique=(\d+)")


def _cli_env(extra_faults=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if extra_faults:
        env["STPU_FAULTS"] = extra_faults
    else:
        env.pop("STPU_FAULTS", None)
    return env

def _run_cli(args, faults=None, timeout=1800):
    """One CLI subprocess; returns (returncode, unique_count|None,
    new TRACE basenames)."""
    before = set(glob.glob(os.path.join(REPO, "TRACE_r*.jsonl")))
    proc = subprocess.run(
        [sys.executable, "-m", "stateright_tpu"] + args,
        cwd=REPO, env=_cli_env(faults),
        capture_output=True, text=True, timeout=timeout,
    )
    unique = None
    m = _DONE_RE.search(proc.stdout)
    if m:
        unique = int(m.group(2))
    after = set(glob.glob(os.path.join(REPO, "TRACE_r*.jsonl")))
    traces = sorted(os.path.basename(p) for p in after - before)
    return proc, unique, traces


def _spawn_mesh(count, wps, n_shards, **kw):
    """A 2pc virtual-mesh sort-merge checker (the degrade cells —
    the sharded engine refuses cand_capacity='auto', so budgets are
    explicit)."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    import math

    capacity = 1 << max(10, math.ceil(2.6 * count + 1.5))
    kw.setdefault("cand_capacity", 4096)
    kw.setdefault("bucket_capacity", 2048)
    return TwoPhaseSys(rm_count=count).checker() \
        .spawn_tpu_sharded_sortmerge(
            n_shards=n_shards,
            capacity=capacity,
            frontier_capacity=max(256, capacity // 4),
            waves_per_sync=wps,
            **kw,
        )


def _spawn(workload, count, wps, **kw):
    if workload == "2pc":
        from stateright_tpu.models.two_phase_commit import TwoPhaseSys

        import math

        capacity = 1 << max(10, math.ceil(2.6 * count + 1.5))
        return TwoPhaseSys(rm_count=count).checker().spawn_tpu_sortmerge(
            capacity=capacity,
            frontier_capacity=max(256, capacity // 4),
            cand_capacity="auto",
            waves_per_sync=wps,
            **kw,
        )
    from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model
    from stateright_tpu.models.paxos_tpu import STRUCTURAL_SIZES

    return (
        paxos_model(PaxosModelCfg(client_count=count, server_count=3))
        .checker()
        .spawn_tpu_sortmerge(
            track_paths=count <= 2,
            cand_capacity="auto",
            waves_per_sync=wps,
            **STRUCTURAL_SIZES[count],
            **kw,
        )
    )


def _mesh_degrade_proof(cell):
    """The flagship acceptance pair (``--mesh-degrade``): a TRACED
    8-shard 2pc rm=5 virtual-mesh run with a PERSISTENT per-shard
    fault injected at a mid-run chunk must automatically degrade to
    fewer shards and complete to the identical 8,832, with the
    resume/degrade-aware trace_diff reporting ZERO global-counter
    divergence vs the uninterrupted traced baseline. Writes the
    TRACE pair as committed artifacts and returns the block the CKPT
    artifact embeds."""
    import warnings as _warnings

    from stateright_tpu import faultinject
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys
    from stateright_tpu.telemetry import (
        RunTracer,
        diff_traces,
        validate_events,
        write_artifacts,
    )

    def spawn(**kw):
        # the dryrun_multichip flagship config (TRACE_r16), at a
        # chunk cadence that puts several boundaries before and
        # after the injected fault
        kw.setdefault("cand_capacity", 2048)
        kw.setdefault("bucket_capacity", 1024)
        return (
            TwoPhaseSys(rm_count=5)
            .checker()
            .spawn_tpu_sharded_sortmerge(
                n_shards=8,
                capacity=1 << 12,
                frontier_capacity=512,
                waves_per_sync=2,
                track_paths=True,
                **kw,
            )
        )

    fault_chunk, fault_shard = 3, 5
    print("mesh degrade acceptance: traced 2pc rm=5 S=8, "
          f"persistent shard fault (shard {fault_shard}) from chunk "
          f"{fault_chunk}")
    tr_base = RunTracer()
    with tr_base.activate():
        b = spawn().join()
    base_n = b.unique_state_count()
    validate_events(tr_base.events)
    jsonl_a, _ = write_artifacts(tr_base)
    print(f"  baseline: {base_n:,} states "
          f"({os.path.basename(jsonl_a)})")

    tmp = tempfile.mkdtemp(prefix="stpu_mesh_degrade_")
    snap = os.path.join(tmp, "mesh.ckpt")
    c = spawn(checkpoint_every=1, checkpoint_path=snap)
    c.degrade_on_fault = True
    c.retry_backoff_sec = 0.01
    tr_deg = RunTracer()
    faultinject.arm("shard_fault", "mid_chunk", fault_chunk,
                    shard=fault_shard)
    err = None
    try:
        with tr_deg.activate():
            with _warnings.catch_warnings():
                _warnings.simplefilter("ignore")
                c.join()
    except Exception as exc:
        err = f"{type(exc).__name__}: {exc}"
    finally:
        faultinject.disarm_all()
        shutil.rmtree(tmp, ignore_errors=True)
    validate_events(tr_deg.events)
    jsonl_b, _ = write_artifacts(tr_deg)
    if err is not None:
        cell("mesh_degrade", "raised", error=err,
             degraded_trace=os.path.basename(jsonl_b))
        return dict(error=err)
    n = c.unique_state_count()
    rep = diff_traces(tr_base.events, tr_deg.events)
    degraded = bool(rep["degrades_b"]) and c.n_shards < 8
    good = (n == base_n and degraded
            and not rep["divergences"] and rep["ok"])
    cell(
        "mesh_degrade",
        "continue-degraded" if good else "count_mismatch",
        count=n, baseline=base_n, to_shards=c.n_shards,
        counter_divergences=len(rep["divergences"]),
    )
    print(f"  trace_diff: {os.path.basename(jsonl_a)} vs "
          f"{os.path.basename(jsonl_b)} — "
          f"{len(rep['divergences'])} counter divergences, "
          f"degraded at wave "
          f"{rep['degrades_b'][0]['wave'] if rep['degrades_b'] else '-'}, "
          f"{'OK' if rep['ok'] else 'FAIL'}")
    return dict(
        baseline_trace=os.path.basename(jsonl_a),
        degraded_trace=os.path.basename(jsonl_b),
        baseline_unique=base_n,
        degraded_unique=n,
        fault_chunk=fault_chunk,
        fault_shard=fault_shard,
        from_shards=8,
        to_shards=int(c.n_shards),
        degrade_wave=(rep["degrades_b"][0]["wave"]
                      if rep["degrades_b"] else None),
        counter_divergences=len(rep["divergences"]),
        diff_ok=bool(rep["ok"]),
    )


def main():
    ap = argparse.ArgumentParser(
        description="fault-injection crash matrix over the "
        "checkpoint/resume path"
    )
    ap.add_argument("--workload", choices=("2pc", "paxos"),
                    default="2pc")
    ap.add_argument("--count", type=int, default=4,
                    help="model size (2pc RMs / paxos clients; "
                    "default 4)")
    ap.add_argument("--seed", type=int, default=0,
                    help="seeds the kill/fault chunk choice "
                    "(faultinject.chunk_for_seed)")
    ap.add_argument("--chunks-hint", type=int, default=5,
                    help="upper bound fed to the seeded chunk pick "
                    "(keep below the workload's real chunk count)")
    ap.add_argument("--waves-per-sync", type=int, default=2,
                    help="chunk cadence for every cell (default 2: "
                    "many boundaries to kill at)")
    ap.add_argument("--trace", action="store_true",
                    help="trace the baseline + resumed runs and embed "
                    "the trace_diff zero-divergence verdict")
    ap.add_argument("--json", action="store_true",
                    help="write an auto-numbered CKPT_r*.json "
                    "artifact")
    ap.add_argument("--root", default=None,
                    help="artifact directory for --json (default: "
                    "the repo root)")
    ap.add_argument("--mesh-degrade", action="store_true",
                    help="additionally run the traced 8-shard 2pc "
                    "rm=5 degrade acceptance pair (TRACE artifacts + "
                    "zero-divergence diff embedded in the JSON)")
    args = ap.parse_args()

    # the mesh cells need virtual devices BEFORE jax initializes
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from stateright_tpu import faultinject
    from stateright_tpu.checkpoint import (
        SnapshotCorruptError,
        SnapshotStaleError,
        load_snapshot,
    )

    wl_cli = {"2pc": "2pc", "paxos": "paxos"}[args.workload]
    wps = args.waves_per_sync
    kill_chunk = 1 + faultinject.chunk_for_seed(
        args.seed, max(args.chunks_hint - 1, 1)
    )
    tmp = tempfile.mkdtemp(prefix="stpu_crash_matrix_")
    snap = os.path.join(tmp, "matrix.ckpt")
    cells: dict = {}
    ok = True

    def cell(name, verdict, **detail):
        nonlocal ok
        # the degrade column's verdict vocabulary: every cell must
        # land on continue-degraded / recovered / refused-loudly —
        # anything else (incl. a hang, which the driver's timeout
        # would surface) fails the matrix
        good = verdict in ("recovered", "refused",
                           "continue-degraded")
        if not good:
            ok = False
        cells[name] = dict(verdict=verdict, **detail)
        print(f"  {name:20s} {verdict:18s} "
              + " ".join(f"{k}={v}" for k, v in detail.items()))

    print(f"crash matrix: {args.workload} count={args.count} "
          f"seed={args.seed} kill_chunk={kill_chunk} "
          f"waves_per_sync={wps}")

    # -- baseline (subprocess CLI, optionally traced) ---------------------
    base_args = [wl_cli, "check-tpu", str(args.count),
                 f"--waves-per-sync={wps}"]
    if args.trace:
        base_args.append("--trace")
    proc, baseline, base_traces = _run_cli(base_args)
    if proc.returncode != 0 or baseline is None:
        print(proc.stdout)
        print(proc.stderr, file=sys.stderr)
        print("crash_matrix: baseline run failed", file=sys.stderr)
        sys.exit(2)
    print(f"  baseline count: {baseline:,}"
          + (f" (trace {base_traces})" if base_traces else ""))

    # -- cell: process kill at a chunk boundary + resume ------------------
    proc, _, _ = _run_cli(
        base_args[:3] + [f"--waves-per-sync={wps}",
                         "--checkpoint-every=1",
                         f"--checkpoint-path={snap}"],
        faults=f"kill@chunk_boundary:{kill_chunk}",
    )
    if proc.returncode != faultinject.KILL_EXIT_CODE:
        cell("kill", "no_kill", returncode=proc.returncode,
             note="run completed before the seeded kill chunk — "
             "lower --chunks-hint")
    elif not os.path.exists(snap):
        cell("kill", "no_snapshot", returncode=proc.returncode)
    else:
        resume_args = base_args[:3] + [
            f"--waves-per-sync={wps}", "--resume",
            f"--checkpoint-path={snap}",
        ]
        if args.trace:
            resume_args.append("--trace")
        proc2, resumed, res_traces = _run_cli(resume_args)
        if proc2.returncode != 0 or resumed != baseline:
            print(proc2.stdout)
            print(proc2.stderr, file=sys.stderr)
            cell("kill", "count_mismatch", baseline=baseline,
                 resumed=resumed, returncode=proc2.returncode)
        else:
            cell("kill", "recovered", kill_chunk=kill_chunk,
                 baseline=baseline, resumed=resumed,
                 **({"trace": res_traces[0]} if res_traces else {}))
        if args.trace and base_traces and res_traces:
            from stateright_tpu.telemetry import (
                diff_traces,
                load_trace,
                validate_events,
            )

            a = load_trace(os.path.join(REPO, base_traces[0]))
            b = load_trace(os.path.join(REPO, res_traces[0]))
            validate_events(a)
            validate_events(b)
            rep = diff_traces(a, b)
            cells["kill"]["trace_diff"] = dict(
                baseline=base_traces[0],
                resumed=res_traces[0],
                resume_wave=rep["resume_wave_b"],
                counter_divergences=len(rep["divergences"]),
                ok=rep["ok"],
            )
            if rep["divergences"] or not rep["ok"]:
                ok = False
                cells["kill"]["verdict"] = "trace_divergence"
            print(f"  trace_diff: {base_traces[0]} vs "
                  f"{res_traces[0]} — "
                  f"{len(rep['divergences'])} counter divergences, "
                  f"resumed at wave {rep['resume_wave_b']}, "
                  f"{'OK' if rep['ok'] else 'FAIL'}")

    # -- snapshot bytes vs the memory ledger ------------------------------
    snapshot_bytes = plan_bytes = None
    if os.path.exists(snap):
        manifest, _ = load_snapshot(snap)
        snapshot_bytes = manifest.get("snapshot_bytes")

    # -- cell: mid-chunk device fault, supervised self-recovery -----------
    c = _spawn(args.workload, args.count, wps,
               checkpoint_every=1,
               checkpoint_path=os.path.join(tmp, "devfault.ckpt"))
    c.retry_backoff_sec = 0.01
    faultinject.arm("raise", "mid_chunk", kill_chunk)
    import warnings as _warnings

    try:
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            c.join()
        n = c.unique_state_count()
        if c.memory_plan:
            plan_bytes = c.memory_plan.get("resident_bytes")
        cell("device_fault",
             "recovered" if n == baseline else "count_mismatch",
             count=n)
    except Exception as exc:
        cell("device_fault", "raised",
             error=f"{type(exc).__name__}: {exc}")
    finally:
        faultinject.disarm_all()

    # -- cells: torn snapshot ---------------------------------------------
    for mode in ("truncate", "flip"):
        name = f"torn_{mode}"
        if not os.path.exists(snap):
            cell(name, "no_snapshot")
            continue
        bad = os.path.join(tmp, f"{name}.ckpt")
        shutil.copy(snap, bad)
        faultinject.corrupt_snapshot(bad, mode, seed=args.seed)
        try:
            _spawn(args.workload, args.count, wps).resume_from(bad)
            cell(name, "undetected")
        except SnapshotCorruptError as exc:
            cell(name, "refused", error=type(exc).__name__)
        except Exception as exc:
            cell(name, "wrong_error",
                 error=f"{type(exc).__name__}: {exc}")

    # -- cells: stale manifest --------------------------------------------
    for field in ("git_sha", "encoding"):
        name = f"stale_{field.replace('git_', '')}"
        if not os.path.exists(snap):
            cell(name, "no_snapshot")
            continue
        bad = os.path.join(tmp, f"{name}.ckpt")
        shutil.copy(snap, bad)
        faultinject.stale_manifest(bad, field)
        try:
            _spawn(args.workload, args.count, wps).resume_from(bad)
            cell(name, "undetected")
        except SnapshotStaleError as exc:
            cell(name, "refused", error=type(exc).__name__)
        except Exception as exc:
            cell(name, "wrong_error",
                 error=f"{type(exc).__name__}: {exc}")

    # -- cell: persistent per-shard fault -> automatic degrade ------------
    # (degrade-and-continue round: the FailurePolicy sees the same
    # shard fail across retries, drops it, re-shards the snapshot
    # onto the survivor — the run must complete to the exact count)
    c = _spawn_mesh(args.count, wps, n_shards=2,
                    checkpoint_every=1,
                    checkpoint_path=os.path.join(tmp, "deg.ckpt"))
    c.degrade_on_fault = True
    c.retry_backoff_sec = 0.01
    faultinject.arm("shard_fault", "mid_chunk", kill_chunk, shard=1)
    try:
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            c.join()
        n = c.unique_state_count()
        if n == baseline and c.n_shards == 1:
            cell("shard_fault_degrade", "continue-degraded",
                 count=n, from_shards=2, to_shards=c.n_shards)
        else:
            cell("shard_fault_degrade", "count_mismatch",
                 count=n, n_shards=c.n_shards, baseline=baseline)
    except Exception as exc:
        cell("shard_fault_degrade", "raised",
             error=f"{type(exc).__name__}: {exc}")
    finally:
        faultinject.disarm_all()

    # -- cell: collective-seam raise, supervised recovery -----------------
    c = _spawn_mesh(args.count, wps, n_shards=2,
                    checkpoint_every=1,
                    checkpoint_path=os.path.join(tmp, "coll.ckpt"))
    c.retry_backoff_sec = 0.01
    faultinject.arm("raise", "collective_seam", kill_chunk,
                    once=True)
    try:
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            c.join()
        n = c.unique_state_count()
        cell("collective_raise",
             "recovered" if n == baseline else "count_mismatch",
             count=n)
    except Exception as exc:
        cell("collective_raise", "raised",
             error=f"{type(exc).__name__}: {exc}")
    finally:
        faultinject.disarm_all()

    # -- cell: chunk-dispatch hang -> watchdog ----------------------------
    # (the livelock shape: a sleep at the dispatch site, no exception
    # — only the watchdog can see it; the verdict must be recovered
    # or refused-loudly-with-attribution, never a hang)
    from stateright_tpu.checkpoint import WatchdogTimeout

    c = _spawn(args.workload, args.count, wps,
               checkpoint_every=1,
               checkpoint_path=os.path.join(tmp, "hang.ckpt"))
    c.retry_backoff_sec = 0.01
    c.watchdog_factor = 5.0
    c.watchdog_floor_sec = 1.0
    c.watchdog_grace_sec = 20.0
    faultinject.arm("hang", "mid_chunk", kill_chunk, hang_sec=25.0)
    try:
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            c.join()
        n = c.unique_state_count()
        cell("hang_watchdog",
             "recovered" if n == baseline else "count_mismatch",
             count=n)
    except WatchdogTimeout as exc:
        # refuse-loudly-with-diagnosis: acceptable where in-process
        # recovery isn't (the attribution names the hung chunk)
        cell("hang_watchdog", "refused",
             error="WatchdogTimeout", chunk=exc.chunk,
             deadline_sec=round(exc.deadline_sec, 3))
    except Exception as exc:
        cell("hang_watchdog", "raised",
             error=f"{type(exc).__name__}: {exc}")
    finally:
        faultinject.disarm_all()

    # -- the flagship degrade acceptance pair (--mesh-degrade) ------------
    mesh_degrade = None
    if args.mesh_degrade:
        mesh_degrade = _mesh_degrade_proof(cell)

    print(f"verdict: {'CLEAN' if ok else 'FAIL'} "
          f"({sum(1 for c in cells.values() if c['verdict'] in ('recovered', 'refused', 'continue-degraded'))}"
          f"/{len(cells)} cells continue-degraded/recover/refuse)")
    if snapshot_bytes is not None:
        print(f"snapshot bytes: {snapshot_bytes:,}"
              + (f" (memplan resident: {plan_bytes:,})"
                 if plan_bytes else ""))

    if args.json:
        from stateright_tpu.artifacts import (
            artifact_path,
            next_round,
            provenance,
        )

        root = args.root or REPO
        path = artifact_path(
            "CKPT", "json", root=root,
            round=next_round(root, stems=("CKPT",)),
        )
        doc = dict(
            workload=args.workload,
            count=args.count,
            seed=args.seed,
            kill_chunk=kill_chunk,
            waves_per_sync=wps,
            baseline_unique=baseline,
            snapshot_bytes=snapshot_bytes,
            memplan_resident_bytes=plan_bytes,
            cells=cells,
            # the degrade column, summarized: which cells landed on
            # continue-degraded and where they degraded to
            degrade_cells={
                name: {k: c[k] for k in
                       ("from_shards", "to_shards") if k in c}
                for name, c in cells.items()
                if c["verdict"] == "continue-degraded"
            },
            mesh_degrade=mesh_degrade,
            clean=ok,
            provenance=provenance(),
        )
        with open(path, "w") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {path}")

    shutil.rmtree(tmp, ignore_errors=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
