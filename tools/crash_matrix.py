#!/usr/bin/env python
"""Crash matrix: the end-to-end proof that checkpoint/resume recovers.

Runs every cell of the fault-injection matrix
(stateright_tpu/faultinject.py) against one workload and verdicts each
as **recovered** (kill or device fault → resumed/retried to the exact
baseline count) or **refused** (torn snapshot, stale manifest → the
named Snapshot* error) — the contract is recover-or-refuse-loudly,
never a silent wrong answer:

* ``kill`` — a SUBPROCESS runs the real CLI check lane with
  ``--checkpoint-every`` and an armed ``STPU_FAULTS`` process kill at
  a seeded chunk boundary (``os._exit(137)``, no cleanup — a real
  preemption), then a second subprocess ``--resume``\\ s from the
  snapshot; the resumed run's final count must equal the baseline's;
* ``device_fault`` — in-process: an injected mid-chunk exception under
  supervision (checkpoint.supervised_run) must self-recover from the
  last snapshot to the identical count in ONE join;
* ``torn_truncate`` / ``torn_flip`` — a valid snapshot damaged on disk
  must be detected (``SnapshotCorruptError``) at resume;
* ``stale_sha`` / ``stale_encoding`` — a rewritten manifest must be
  refused (``SnapshotStaleError``) at resume.

``--trace`` additionally runs the baseline and the resumed half
traced (``TRACE_r*`` artifacts land in the repo root) and embeds the
``tools/trace_diff.py`` verdict: the resumed run's wave stream must
align with the uninterrupted baseline at ZERO counter divergence
(telemetry's resume-aware alignment — pre-kill waves died with the
killed process, the overlap must match exactly).

``--json`` writes an auto-numbered ``CKPT_r*.json`` artifact (its own
round sequence like MEM/LAT/COMM, via stateright_tpu/artifacts.py)
carrying the per-cell verdicts, the snapshot byte size vs the memory
ledger's predicted resident bytes, and the trace-diff block.
bench.py embeds the newest CKPT artifact beside LINT/COMM
(``artifacts.latest_ckpt_summary``).

Usage:
  python tools/crash_matrix.py                       # 2pc rm=4, fast
  python tools/crash_matrix.py --workload paxos --count 4 --trace --json
  python tools/crash_matrix.py --seed 7 --json

Exit status: 0 all cells recover-or-refuse, 1 any cell failed,
2 bad input.
"""

import argparse
import glob
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_DONE_RE = re.compile(r"Done\. states=(\d+), unique=(\d+)")


def _cli_env(extra_faults=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if extra_faults:
        env["STPU_FAULTS"] = extra_faults
    else:
        env.pop("STPU_FAULTS", None)
    return env

def _run_cli(args, faults=None, timeout=1800):
    """One CLI subprocess; returns (returncode, unique_count|None,
    new TRACE basenames)."""
    before = set(glob.glob(os.path.join(REPO, "TRACE_r*.jsonl")))
    proc = subprocess.run(
        [sys.executable, "-m", "stateright_tpu"] + args,
        cwd=REPO, env=_cli_env(faults),
        capture_output=True, text=True, timeout=timeout,
    )
    unique = None
    m = _DONE_RE.search(proc.stdout)
    if m:
        unique = int(m.group(2))
    after = set(glob.glob(os.path.join(REPO, "TRACE_r*.jsonl")))
    traces = sorted(os.path.basename(p) for p in after - before)
    return proc, unique, traces


def _spawn(workload, count, wps, **kw):
    if workload == "2pc":
        from stateright_tpu.models.two_phase_commit import TwoPhaseSys

        import math

        capacity = 1 << max(10, math.ceil(2.6 * count + 1.5))
        return TwoPhaseSys(rm_count=count).checker().spawn_tpu_sortmerge(
            capacity=capacity,
            frontier_capacity=max(256, capacity // 4),
            cand_capacity="auto",
            waves_per_sync=wps,
            **kw,
        )
    from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model
    from stateright_tpu.models.paxos_tpu import STRUCTURAL_SIZES

    return (
        paxos_model(PaxosModelCfg(client_count=count, server_count=3))
        .checker()
        .spawn_tpu_sortmerge(
            track_paths=count <= 2,
            cand_capacity="auto",
            waves_per_sync=wps,
            **STRUCTURAL_SIZES[count],
            **kw,
        )
    )


def main():
    ap = argparse.ArgumentParser(
        description="fault-injection crash matrix over the "
        "checkpoint/resume path"
    )
    ap.add_argument("--workload", choices=("2pc", "paxos"),
                    default="2pc")
    ap.add_argument("--count", type=int, default=4,
                    help="model size (2pc RMs / paxos clients; "
                    "default 4)")
    ap.add_argument("--seed", type=int, default=0,
                    help="seeds the kill/fault chunk choice "
                    "(faultinject.chunk_for_seed)")
    ap.add_argument("--chunks-hint", type=int, default=5,
                    help="upper bound fed to the seeded chunk pick "
                    "(keep below the workload's real chunk count)")
    ap.add_argument("--waves-per-sync", type=int, default=2,
                    help="chunk cadence for every cell (default 2: "
                    "many boundaries to kill at)")
    ap.add_argument("--trace", action="store_true",
                    help="trace the baseline + resumed runs and embed "
                    "the trace_diff zero-divergence verdict")
    ap.add_argument("--json", action="store_true",
                    help="write an auto-numbered CKPT_r*.json "
                    "artifact")
    ap.add_argument("--root", default=None,
                    help="artifact directory for --json (default: "
                    "the repo root)")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from stateright_tpu import faultinject
    from stateright_tpu.checkpoint import (
        SnapshotCorruptError,
        SnapshotStaleError,
        load_snapshot,
    )

    wl_cli = {"2pc": "2pc", "paxos": "paxos"}[args.workload]
    wps = args.waves_per_sync
    kill_chunk = 1 + faultinject.chunk_for_seed(
        args.seed, max(args.chunks_hint - 1, 1)
    )
    tmp = tempfile.mkdtemp(prefix="stpu_crash_matrix_")
    snap = os.path.join(tmp, "matrix.ckpt")
    cells: dict = {}
    ok = True

    def cell(name, verdict, **detail):
        nonlocal ok
        good = verdict in ("recovered", "refused")
        if not good:
            ok = False
        cells[name] = dict(verdict=verdict, **detail)
        print(f"  {name:16s} {verdict:10s} "
              + " ".join(f"{k}={v}" for k, v in detail.items()))

    print(f"crash matrix: {args.workload} count={args.count} "
          f"seed={args.seed} kill_chunk={kill_chunk} "
          f"waves_per_sync={wps}")

    # -- baseline (subprocess CLI, optionally traced) ---------------------
    base_args = [wl_cli, "check-tpu", str(args.count),
                 f"--waves-per-sync={wps}"]
    if args.trace:
        base_args.append("--trace")
    proc, baseline, base_traces = _run_cli(base_args)
    if proc.returncode != 0 or baseline is None:
        print(proc.stdout)
        print(proc.stderr, file=sys.stderr)
        print("crash_matrix: baseline run failed", file=sys.stderr)
        sys.exit(2)
    print(f"  baseline count: {baseline:,}"
          + (f" (trace {base_traces})" if base_traces else ""))

    # -- cell: process kill at a chunk boundary + resume ------------------
    proc, _, _ = _run_cli(
        base_args[:3] + [f"--waves-per-sync={wps}",
                         "--checkpoint-every=1",
                         f"--checkpoint-path={snap}"],
        faults=f"kill@chunk_boundary:{kill_chunk}",
    )
    if proc.returncode != faultinject.KILL_EXIT_CODE:
        cell("kill", "no_kill", returncode=proc.returncode,
             note="run completed before the seeded kill chunk — "
             "lower --chunks-hint")
    elif not os.path.exists(snap):
        cell("kill", "no_snapshot", returncode=proc.returncode)
    else:
        resume_args = base_args[:3] + [
            f"--waves-per-sync={wps}", "--resume",
            f"--checkpoint-path={snap}",
        ]
        if args.trace:
            resume_args.append("--trace")
        proc2, resumed, res_traces = _run_cli(resume_args)
        if proc2.returncode != 0 or resumed != baseline:
            print(proc2.stdout)
            print(proc2.stderr, file=sys.stderr)
            cell("kill", "count_mismatch", baseline=baseline,
                 resumed=resumed, returncode=proc2.returncode)
        else:
            cell("kill", "recovered", kill_chunk=kill_chunk,
                 baseline=baseline, resumed=resumed,
                 **({"trace": res_traces[0]} if res_traces else {}))
        if args.trace and base_traces and res_traces:
            from stateright_tpu.telemetry import (
                diff_traces,
                load_trace,
                validate_events,
            )

            a = load_trace(os.path.join(REPO, base_traces[0]))
            b = load_trace(os.path.join(REPO, res_traces[0]))
            validate_events(a)
            validate_events(b)
            rep = diff_traces(a, b)
            cells["kill"]["trace_diff"] = dict(
                baseline=base_traces[0],
                resumed=res_traces[0],
                resume_wave=rep["resume_wave_b"],
                counter_divergences=len(rep["divergences"]),
                ok=rep["ok"],
            )
            if rep["divergences"] or not rep["ok"]:
                ok = False
                cells["kill"]["verdict"] = "trace_divergence"
            print(f"  trace_diff: {base_traces[0]} vs "
                  f"{res_traces[0]} — "
                  f"{len(rep['divergences'])} counter divergences, "
                  f"resumed at wave {rep['resume_wave_b']}, "
                  f"{'OK' if rep['ok'] else 'FAIL'}")

    # -- snapshot bytes vs the memory ledger ------------------------------
    snapshot_bytes = plan_bytes = None
    if os.path.exists(snap):
        manifest, _ = load_snapshot(snap)
        snapshot_bytes = manifest.get("snapshot_bytes")

    # -- cell: mid-chunk device fault, supervised self-recovery -----------
    c = _spawn(args.workload, args.count, wps,
               checkpoint_every=1,
               checkpoint_path=os.path.join(tmp, "devfault.ckpt"))
    c.retry_backoff_sec = 0.01
    faultinject.arm("raise", "mid_chunk", kill_chunk)
    import warnings as _warnings

    try:
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            c.join()
        n = c.unique_state_count()
        if c.memory_plan:
            plan_bytes = c.memory_plan.get("resident_bytes")
        cell("device_fault",
             "recovered" if n == baseline else "count_mismatch",
             count=n)
    except Exception as exc:
        cell("device_fault", "raised",
             error=f"{type(exc).__name__}: {exc}")
    finally:
        faultinject.disarm_all()

    # -- cells: torn snapshot ---------------------------------------------
    for mode in ("truncate", "flip"):
        name = f"torn_{mode}"
        if not os.path.exists(snap):
            cell(name, "no_snapshot")
            continue
        bad = os.path.join(tmp, f"{name}.ckpt")
        shutil.copy(snap, bad)
        faultinject.corrupt_snapshot(bad, mode, seed=args.seed)
        try:
            _spawn(args.workload, args.count, wps).resume_from(bad)
            cell(name, "undetected")
        except SnapshotCorruptError as exc:
            cell(name, "refused", error=type(exc).__name__)
        except Exception as exc:
            cell(name, "wrong_error",
                 error=f"{type(exc).__name__}: {exc}")

    # -- cells: stale manifest --------------------------------------------
    for field in ("git_sha", "encoding"):
        name = f"stale_{field.replace('git_', '')}"
        if not os.path.exists(snap):
            cell(name, "no_snapshot")
            continue
        bad = os.path.join(tmp, f"{name}.ckpt")
        shutil.copy(snap, bad)
        faultinject.stale_manifest(bad, field)
        try:
            _spawn(args.workload, args.count, wps).resume_from(bad)
            cell(name, "undetected")
        except SnapshotStaleError as exc:
            cell(name, "refused", error=type(exc).__name__)
        except Exception as exc:
            cell(name, "wrong_error",
                 error=f"{type(exc).__name__}: {exc}")

    print(f"verdict: {'CLEAN' if ok else 'FAIL'} "
          f"({sum(1 for c in cells.values() if c['verdict'] in ('recovered', 'refused'))}"
          f"/{len(cells)} cells recover-or-refuse)")
    if snapshot_bytes is not None:
        print(f"snapshot bytes: {snapshot_bytes:,}"
              + (f" (memplan resident: {plan_bytes:,})"
                 if plan_bytes else ""))

    if args.json:
        from stateright_tpu.artifacts import (
            artifact_path,
            next_round,
            provenance,
        )

        root = args.root or REPO
        path = artifact_path(
            "CKPT", "json", root=root,
            round=next_round(root, stems=("CKPT",)),
        )
        doc = dict(
            workload=args.workload,
            count=args.count,
            seed=args.seed,
            kill_chunk=kill_chunk,
            waves_per_sync=wps,
            baseline_unique=baseline,
            snapshot_bytes=snapshot_bytes,
            memplan_resident_bytes=plan_bytes,
            cells=cells,
            clean=ok,
            provenance=provenance(),
        )
        with open(path, "w") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {path}")

    shutil.rmtree(tmp, ignore_errors=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
