#!/usr/bin/env python
"""Trace-diff gate: align two TRACE_r*.jsonl run-telemetry artifacts
wave-by-wave and price the per-phase deltas.

This is the mechanism A/B rounds record their before/after through
(ROADMAP: the BENCH_r06 chip re-measure, the carry-rework ablation):
instead of two numbers typed into PERF.md, each side is a trace
artifact and this tool is the comparison —

* **wave alignment** — the per-wave counters (frontier rows,
  candidates, new states, running unique total) must MATCH: two
  traces of the same workload explore the same space, so any
  divergence means the runs are not comparable (different model,
  bounds, or a correctness regression) and the gate fails regardless
  of timing.
* **shard-aware alignment** (round 11) — traces carrying per-shard
  ``shard_wave`` events additionally align each wave's SHARD rows as
  a MULTISET of counter tuples: the (owner, fp) partition is
  deterministic up to shard numbering, so a mesh relabeling passes
  while a redistributed partition — even one whose GLOBAL sums
  match — fails. The global counters must still match exactly.
* **per-phase deltas** — host spans (compile, reconstruction,
  property checks), the chunk dispatch/fetch wall split, the wave
  wall, and the run total, each reported as A/B/delta/relative.
* **memory alignment** (round 12) — traces carrying ``memory_plan``
  events must declare IDENTICAL resident layouts and ladder-class
  staging (plan shapes are config: a mismatch fails the gate like a
  counter divergence), while MEASURED bytes — compiled temp bytes,
  the live watermark peak — compare relative under ``--threshold``,
  so jax-version allocator skew doesn't false-positive.
* **latency alignment** (round 14) — traces carrying
  ``latency_profile`` / ``verdict`` events additionally compare the
  wall-attribution lanes (time-to-first-wave, dispatch net of
  compile, the sync-floor fetch total, compile cold wall) and
  per-property time-to-verdict. These lanes regress when
  ``B - A > max(--min-sec, --threshold * A)`` — the absolute floor
  matters: a multi-second forced cold compile against a 0-second
  warm ledger, or an injected host stall on a millisecond fetch
  floor, must flag even though the A side is under the relative
  noise gate. A property that settles by discovery on one side and
  exhaustion on the other is a DIVERGENCE (the runs answered the
  property differently). Sides without latency events skip the
  block, so pre-round-14 baselines keep diffing.
* **tier alignment** (round 16) — traces carrying ``tier_spill``
  events (the tiered visited set, stateright_tpu/tier.py) compare
  spill counts and cold-tier rows/bytes EXACTLY (two tiered runs of
  one workload at one hot ceiling spill identically — a mismatch is
  a divergence) and the spill/ingest walls under the latency bar.
  A side with no tier events skips the block: a forced-spill run
  diffs against the all-resident baseline on the wave counters
  alone — which is exactly the tiered-dedup exactness proof.
* **certificate alignment** (round 21) — reduction runs carry
  ``soundness_certified`` in the run_begin lane config (the
  soundness analyzer's verdict, analysis/soundness.py). A
  certified ↔ refused flip between the two traces is a DIVERGENCE
  (field ``soundness_certified``, wave ``null``): the compared
  reductions do not carry the same soundness guarantee, so the runs
  are not an A/B of one workload. Sides without the field (no
  reduction on) skip the block.
* **regression threshold** — exit nonzero when any phase at least
  ``--min-sec`` long on the A side grew by more than ``--threshold``
  (relative), or on any wave divergence.

Usage:
  python tools/trace_diff.py TRACE_r07.jsonl TRACE_r08.jsonl
  python tools/trace_diff.py a.jsonl b.jsonl --threshold 0.05
  python tools/trace_diff.py a.jsonl b.jsonl --run-a 0 --run-b 2

Exit status: 0 clean, 1 regression/divergence, 2 bad input.
"""

import argparse
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main():
    ap = argparse.ArgumentParser(
        description="diff two TRACE_r*.jsonl run-telemetry artifacts"
    )
    ap.add_argument("a", help="baseline trace (JSONL)")
    ap.add_argument("b", help="candidate trace (JSONL)")
    ap.add_argument(
        "--threshold", type=float, default=0.10,
        help="relative per-phase regression bar (default 0.10 = +10%%)",
    )
    ap.add_argument(
        "--min-sec", type=float, default=0.05,
        help="ignore phases shorter than this on the A side "
        "(noise floor, default 0.05s)",
    )
    ap.add_argument(
        "--run-a", type=int, default=None,
        help="run index inside A (default: the last run)",
    )
    ap.add_argument(
        "--run-b", type=int, default=None,
        help="run index inside B (default: the last run)",
    )
    args = ap.parse_args()

    from stateright_tpu.telemetry import (
        diff_traces,
        format_diff,
        load_trace,
        validate_events,
    )

    try:
        a = load_trace(args.a)
        b = load_trace(args.b)
        validate_events(a)
        validate_events(b)
    except (OSError, ValueError) as exc:
        print(f"trace_diff: bad input: {exc}", file=sys.stderr)
        sys.exit(2)

    try:
        report = diff_traces(
            a, b,
            run_a=args.run_a, run_b=args.run_b,
            threshold=args.threshold, min_sec=args.min_sec,
        )
    except IndexError:
        print("trace_diff: a file contains no runs", file=sys.stderr)
        sys.exit(2)

    print(format_diff(report))
    sys.exit(0 if report["ok"] else 1)


if __name__ == "__main__":
    main()
