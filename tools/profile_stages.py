#!/usr/bin/env python
"""Per-STAGE profile of the sparse sort-merge wave at real workload
shapes with REAL mid-run data (VERDICT r4: explain where the
~76ms/wave at paxos check 4 goes, and why check 5 runs at half the
per-state rate of check 4).

Method: run the real engine with ``target_state_count`` ≈ half the
space and ``keep_final_carry`` set, so the final carry's frontier is a
genuine mid-growth wave's new-state set and the visited array holds
the genuine prefix. Then re-run each wave stage in isolation on that
data, amortized over REPS in-jit repetitions (the axon tunnel hides
per-dispatch execution, so each measured op runs inside one jitted
fori_loop with a full-reduction fold that defeats DCE).

Usage:
  python tools/profile_stages.py --paxos 4
  python tools/profile_stages.py --paxos 5
  python tools/profile_stages.py --twopc 8
  python tools/profile_stages.py --paxos 4 --wave-profile   # per-wave ms
  python tools/profile_stages.py --paxos 4 --wave-wall      # out-of-stage
                                  # wall + per-HLO-category attribution
                                  # (stateright_tpu/wavewall.py)
  python tools/profile_stages.py --micro    # primitive costs at engine
                                  # row counts, synthetic keys (the
                                  # retired profile_sortmerge.py's
                                  # post-round-10 successor)

Per-wave WALL times for a real run come from ``--trace=deep`` +
tools/latency_report.py these days — this tool is for isolating
stages, not timing runs.
"""

import argparse
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

REPS = 8


def _timed_raw(build, args, reps=REPS):
    """Time `reps` sequential applications of build inside one jitted
    program. build(i, carry) -> carry MUST fold a FULL reduction of
    each stage output back into the carry — folding a single element
    lets XLA dead-code-eliminate the rest of the stage (the round-5
    profiler bug: step+fp showed 1.5ms because only succ[0] was
    live)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def run(*arrs):
        out = lax.fori_loop(0, reps, build, arrs)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        # Consume EVERY carry element — returning only out[0] lets XLA
        # DCE stages that fold their work into a later carry slot.
        return sum(
            jnp.sum(a.reshape(-1)[:1].astype(jnp.uint32)) for a in out
        )

    f = jax.jit(run)
    float(f(*args))  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.monotonic()
        float(f(*args))
        best = min(best, time.monotonic() - t0)
    return best


def _timed(build, args, reps=REPS):
    """ms per op, empty-loop baseline (dispatch floor + carry
    movement at the same shapes) subtracted."""
    base = _timed_raw(lambda i, c: c, args, reps)
    return (_timed_raw(build, args, reps) - base) / reps * 1000.0


def _fold(x):
    """Full-output reduction to defeat DCE: cheap relative to any
    measured stage (one pass over x)."""
    import jax.numpy as jnp

    return jnp.sum(x.reshape(-1).astype(jnp.uint32)) % jnp.uint32(2)


def _spawn(kind, n, caps, target=None, waves_per_sync=64,
           optimize=True):
    encoded = None
    if kind == "paxos":
        from stateright_tpu.models.paxos import PaxosModelCfg, paxos_model

        b = paxos_model(
            PaxosModelCfg(client_count=n, server_count=3)
        ).checker()
    elif kind == "paxos-compiled":
        # The compiled paxos lane (round 23): same actor model, the
        # encoding comes from the generic compiler (reachable-mode
        # harvest — paid here, outside the profiled stages).
        from stateright_tpu.models.paxos import (
            PaxosModelCfg, paxos_compiled_encoded, paxos_model,
        )

        cfg = PaxosModelCfg(client_count=n, server_count=3)
        b = paxos_model(cfg).checker()
        encoded = paxos_compiled_encoded(cfg, optimize=optimize)
    elif kind == "twopc-compiled":
        # Compiled count-comparable 2pc system model; ``optimize``
        # toggles the codegen optimizer for per-stage ablation A/Bs
        # (the PERF.md §compiled-parity before/after rows).
        from stateright_tpu.models.two_phase_commit_actors import (
            two_phase_sys_actor_model,
            two_phase_sys_compiled_encoded,
        )

        b = two_phase_sys_actor_model(n).checker()
        encoded = two_phase_sys_compiled_encoded(n, optimize=optimize)
    else:
        from stateright_tpu.models.two_phase_commit import TwoPhaseSys

        b = TwoPhaseSys(rm_count=n).checker()
    if target is not None:
        b = b.target_state_count(target)
    return b.spawn_tpu_sortmerge(
        track_paths=False, waves_per_sync=waves_per_sync,
        **({"encoded": encoded} if encoded is not None else {}),
        **caps
    )


def stage_profile(kind, n, caps, target, optimize=True):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from stateright_tpu.checkers.tpu_sortmerge import (
        _SENT,
        _divisor_at_least,
        _ladder,
        sparse_pair_candidates,
    )
    from stateright_tpu.checkers.tpu import frontier_props_t
    from stateright_tpu.encoding import pair_step_seam
    from stateright_tpu.ops.fingerprint import fingerprint_u32v_t

    print(f"\n## stage profile: {kind} {n} (target={target})")
    c = _spawn(kind, n, caps, target=target, optimize=optimize)
    c.keep_final_carry = True
    c.join()
    carry = c._final_carry
    enc = c.encoded
    # The resident frontier is the transposed [W, F] block (round 9,
    # PERF.md §layout); every stage below mirrors the engine's
    # transposed invocation, including the one row-major seam
    # transpose feeding the pair-step gathers.
    frontier = carry["frontier"]
    # Frontier rows past the last wave's class-local block are STALE
    # (round 6 carry rework) — the carried n_frontier is the live-row
    # count (the live rows are always a dense prefix).
    n_rows = int(np.asarray(carry["n_frontier"]))
    V_cnt = int(np.asarray(carry["new"]))
    print(f"captured frontier rows={n_rows}  visited={V_cnt}  "
          f"depth={int(np.asarray(carry['depth']))}")

    K, W = enc.max_actions, enc.width
    F = c.frontier_capacity
    f_ladder = _ladder(c.f_min, F, c.ladder_step)
    v_ladder = _ladder(c.v_min, c.capacity, c.v_ladder_step)
    F_f = next(v for v in f_ladder if v >= n_rows)
    V_v = next(v for v in v_ladder if v >= V_cnt)
    EV = c._pair_width()
    B_user = min(c.cand_capacity or F * K, F * K)
    NPg = F_f * EV
    B_p = min(B_user, NPg)
    compaction = NPg > B_p
    want_tiles = -(-NPg // c.tile_rows)
    if F_f == F:
        want_tiles = max(want_tiles, c.tiles)
    if compaction:
        # Mirror make_sparse_wave's packed-append headroom clamp so
        # the profiled Ba/NT/T match what the engine actually runs.
        want_tiles = max(want_tiles, -(-(4 * NPg) // max(B_p, 1)))
    NT = _divisor_at_least(F_f, want_tiles) if compaction else 1
    T = F_f // NT
    Ba = (B_p + T * EV) if compaction else NPg
    # Chunk gate mirrors the engine: PADDED row cost (~512 B per
    # 128-lane group on TPU), not unpadded W*4.
    row_pad = -(-W // 128) * 512
    chunked = compaction and (Ba * row_pad > c.flat_budget_bytes)
    NC = Bc = 0
    if chunked:
        NC = -(-(Ba * row_pad) // c.flat_budget_bytes)
        Bc = -(-Ba // NC)
        Ba = NC * Bc
    print(f"class: F_f={F_f} V_v={V_v} K={K} W={W} EV={EV} "
          f"B_p={B_p} NT={NT} Ba={Ba} chunked={chunked}")

    frontier_f = frontier[:, :F_f]
    fval_f = jnp.arange(F_f) < n_rows
    ebits_f = carry["ebits"][:F_f]
    props = list(c.model.properties())
    from stateright_tpu.model import Expectation

    evt_idx = [i for i, p in enumerate(props)
               if p.expectation == Expectation.EVENTUALLY]

    results = {}
    acc0 = jnp.zeros(8, jnp.uint32)

    # -- stage: property conditions over the frontier -------------------
    def s_props(i, a):
        fr, acc = a
        fr = fr.at[0, 0].set(fr[0, 0] ^ i.astype(jnp.uint32))
        cond, eb, f_lo, f_hi = frontier_props_t(
            enc, props, evt_idx, fr, fval_f, ebits_f
        )
        acc = acc.at[0].add(
            _fold(cond) + _fold(eb) + _fold(f_lo) + _fold(f_hi)
        )
        return fr, acc

    results["props(frontier)"] = _timed(s_props, (frontier_f, acc0))

    # -- stage: enabled mask only (the [F,K] predicate pass) ------------
    from stateright_tpu.checkers.tpu_sortmerge import (
        frontier_enabled_bits,
    )

    mb = c.mask_budget_cells

    def mask_only(fr):
        # THE engine's mask pass (one shared home, the way
        # encoding.pair_step_seam is the one pair-seam home): the
        # profiler times the exact pipeline sparse_pair_candidates
        # runs, transposed invocation, tiling and all.
        return frontier_enabled_bits(
            enc, fr, fval_f, jnp.bool_(True),
            mask_budget_cells=c.mask_budget_cells,
        )

    def s_mask(i, a):
        fr, acc = a
        fr = fr.at[0, 0].set(fr[0, 0] ^ i.astype(jnp.uint32))
        bits, cnt = mask_only(fr)
        acc = acc.at[0].add(_fold(bits) + _fold(cnt))
        return fr, acc

    results["enabled-mask [F,K]"] = _timed(s_mask, (frontier_f, acc0))

    # -- stage: full pair pipeline (mask + peel + compaction) -----------
    def s_pairs(i, a):
        fr, acc = a
        fr = fr.at[0, 0].set(fr[0, 0] ^ i.astype(jnp.uint32))
        pidx, live, pslot, cnt, n_pairs, ovf, tmax = (
            sparse_pair_candidates(
                enc, fr, fval_f, jnp.bool_(True),
                EV=EV, B_p=B_p, NT=NT, T=T,
                mask_budget_cells=mb, Ba=Ba,
            )
        )
        acc = acc.at[0].add(
            _fold(pidx) + _fold(pslot) + _fold(cnt) + n_pairs
        )
        return fr, acc

    results["pairs(mask+peel+compact)"] = _timed(
        s_pairs, (frontier_f, acc0)
    )

    # materialize real pairs once for the downstream stages
    pidx, live, pslot, cnt, n_pairs, ovf, tmax = jax.jit(
        lambda fr: sparse_pair_candidates(
            enc, fr, fval_f, jnp.bool_(True),
            EV=EV, B_p=B_p, NT=NT, T=T, mask_budget_cells=mb, Ba=Ba,
        )
    )(frontier_f)
    n_pairs_i = int(np.asarray(n_pairs))
    print(f"real pairs this wave: {n_pairs_i} (Ba={Ba})")

    # The engine's backend-adaptive pair-state seam, from its ONE
    # home (encoding.pair_step_seam) — the profiler times exactly the
    # policy the engines run.
    cpu_backend = jax.default_backend() == "cpu"
    step_cols, make_pair_states = pair_step_seam(enc, cpu_backend)

    def pair_states(fr, idx):
        return make_pair_states(fr, fr)(idx)

    has_boundary = not getattr(enc, "trivial_boundary", False)

    # -- stage: step + fingerprint over Ba pairs ------------------------
    def eval_block(fr, pidx_b, live_b, slot_b):
        from stateright_tpu.encoding import within_boundary_cols

        prow_b = pidx_b // jnp.uint32(EV)
        succ_t, ptr_b, hard_b = step_cols(
            pair_states(fr, prow_b), slot_b
        )
        ok = live_b
        if hard_b is not None:
            ok = ok & ~hard_b
        if has_boundary:
            inb = within_boundary_cols(enc, succ_t)
            ok = ok & inb
        if ptr_b is not None:
            ok = ok & ~ptr_b
        lo, hi = fingerprint_u32v_t(succ_t, jnp)
        lo = jnp.where(ok, lo, jnp.uint32(_SENT))
        hi = jnp.where(ok, hi, jnp.uint32(_SENT))
        return lo, hi

    if chunked:
        def s_stepfp(i, a):
            fr, pi, acc = a
            pi = pi.at[0].set(pi[0] ^ (i.astype(jnp.uint32) & 1))

            def fchunk(ti, fc_acc):
                cl, ch = fc_acc
                off = ti * Bc
                lo, hi = eval_block(
                    fr,
                    lax.dynamic_slice(pi, (off,), (Bc,)),
                    lax.dynamic_slice(live, (off,), (Bc,)),
                    lax.dynamic_slice(pslot, (off,), (Bc,)),
                )
                return (
                    lax.dynamic_update_slice(cl, lo, (off,)),
                    lax.dynamic_update_slice(ch, hi, (off,)),
                )

            cl, ch = lax.fori_loop(
                0, NC, fchunk,
                (jnp.full(Ba, _SENT, jnp.uint32),
                 jnp.full(Ba, _SENT, jnp.uint32)),
            )
            acc = acc.at[0].add(_fold(cl) + _fold(ch))
            return fr, pi, acc
    else:
        def s_stepfp(i, a):
            fr, pi, acc = a
            pi = pi.at[0].set(pi[0] ^ (i.astype(jnp.uint32) & 1))
            lo, hi = eval_block(fr, pi, live, pslot)
            acc = acc.at[0].add(_fold(lo) + _fold(hi))
            return fr, pi, acc

    results[f"step+fp ({Ba} pairs)"] = _timed(
        s_stepfp, (frontier_f, pidx, acc0)
    )

    # real candidate keys for the merge stages
    ck_lo, ck_hi = jax.jit(
        lambda fr: eval_block(fr, pidx, live, pslot)
    )(frontier_f)

    # -- stage: symmetry canonicalization over the successor block ------
    # (only when the encoding declares a device rewrite spec; the
    # engine runs this between step and fingerprint when --symmetry is
    # armed, so its cost rides the same [W, B] transposed layout)
    from stateright_tpu.encoding import device_rewrite_spec

    sym_spec = device_rewrite_spec(enc)
    if sym_spec is not None:
        from stateright_tpu.ops.canonical import canonicalize_t

        Bcn = Bc if chunked else Ba
        succ_t_d = jax.jit(
            lambda fr: step_cols(
                pair_states(fr, pidx[:Bcn] // jnp.uint32(EV)),
                pslot[:Bcn],
            )[0]
        )(frontier_f)

        def s_canon(i, a):
            st, acc = a
            st = st.at[0, 0].set(st[0, 0] ^ (i.astype(jnp.uint32) & 1))
            ct = canonicalize_t(sym_spec, st, jnp)
            acc = acc.at[0].add(_fold(ct))
            return st, acc

        results[f"canonicalize ({Bcn} succ)"] = _timed(
            s_canon, (succ_t_d, acc0)
        )

    v_lo_full, v_hi_full = carry["vkeys"][0], carry["vkeys"][1]
    M = V_v + Ba

    # -- merge_kernel stages (round 10, PERF.md §merge-kernel): the
    # streaming visited-dedup path the engine actually runs — B-row
    # candidate order sort, membership pass, winner merge append —
    # with the RETIRED (V_v + B)-row rebuild path re-timed below as
    # the per-wave A/B denominator.
    from stateright_tpu.ops.merge import (
        compact_winners, member_sorted, merge_sorted,
    )

    mi = c.merge_impl
    NF = min(F, Ba)
    print(f"merge_impl: {mi}")

    def s_csort(i, a):
        kh, kl, acc = a
        kh = kh.at[0].set(kh[0] ^ (i.astype(jnp.uint32) & 1))
        pos = jnp.arange(1, Ba + 1, dtype=jnp.uint32)
        s_hi, s_lo, s_pos = lax.sort((kh, kl, pos), num_keys=2)
        acc = acc.at[0].add(_fold(s_hi) + _fold(s_lo) + _fold(s_pos))
        return kh, kl, acc

    results[f"merge_kernel: cand-sort3 ({Ba})"] = _timed(
        s_csort, (ck_hi, ck_lo, acc0)
    )

    s_hi_d, s_lo_d = jax.jit(
        lambda kh, kl: lax.sort((kh, kl), num_keys=2)
    )(ck_hi, ck_lo)

    def s_member(i, a):
        vh, vl, qh, ql, acc = a
        vl = vl.at[0].set(vl[0] ^ (i.astype(jnp.uint32) & 1))
        m = member_sorted(vl[:V_v], vh[:V_v], ql, qh, impl=mi)
        acc = acc.at[0].add(_fold(m))
        return vh, vl, qh, ql, acc

    results[f"merge_kernel: member ({V_v} | {Ba})"] = _timed(
        s_member, (v_hi_full, v_lo_full, s_hi_d, s_lo_d, acc0)
    )

    def s_wcompact(i, a):
        # the order-preserving winner compaction (ops/merge.py,
        # impl-adaptive: O(B) rank scatter on the XLA fallback, one
        # 4-lane B-row sort on Pallas/TPU): part of the streaming
        # path's per-wave bill
        nw, sp, sl, sh, acc = a
        nw = nw.at[0].set(nw[0] ^ (i & 1).astype(bool))
        np_, wl, wh = compact_winners(nw, sp, sl, sh, NF, impl=mi)
        acc = acc.at[0].add(_fold(np_) + _fold(wl) + _fold(wh))
        return nw, sp, sl, sh, acc

    isnew_d = jnp.arange(Ba, dtype=jnp.uint32) % 5 != 0
    spos_d = jnp.arange(1, Ba + 1, dtype=jnp.uint32)
    results[f"merge_kernel: winner-compact ({Ba})"] = _timed(
        s_wcompact, (isnew_d, spos_d, s_lo_d, s_hi_d, acc0)
    )

    w_hi_d = s_hi_d[:NF]
    w_lo_d = s_lo_d[:NF]

    def s_append(i, a):
        vh, vl, wh, wl, acc = a
        vl = vl.at[0].set(vl[0] ^ (i.astype(jnp.uint32) & 1))
        m_lo, m_hi = merge_sorted(
            vl[:V_v], vh[:V_v], wl, wh, impl=mi
        )
        acc = acc.at[0].add(_fold(m_lo) + _fold(m_hi))
        return vh, vl, wh, wl, acc

    results[f"merge_kernel: append ({V_v}+{NF})"] = _timed(
        s_append, (v_hi_full, v_lo_full, w_hi_d, w_lo_d, acc0)
    )

    # -- RETIRED rebuild path (rounds 5-9), kept as the A/B record:
    # the (V_v + B)-row stable 3-lane concat sort + the (V_v + B)-row
    # winner-position sort the streaming path replaced ------------------
    def s_merge3(i, a):
        vh, vl, kh, kl, acc = a
        kh = kh.at[0].set(kh[0] ^ (i.astype(jnp.uint32) & 1))
        m_hi = jnp.concatenate([vh[:V_v], kh])
        m_lo = jnp.concatenate([vl[:V_v], kl])
        m_pos = jnp.concatenate([
            jnp.zeros(V_v, jnp.uint32),
            jnp.arange(1, Ba + 1, dtype=jnp.uint32),
        ])
        m_hi, m_lo, m_pos = lax.sort((m_hi, m_lo, m_pos), num_keys=2)
        acc = acc.at[0].add(_fold(m_hi) + _fold(m_lo) + _fold(m_pos))
        return vh, vl, kh, kl, acc

    results[f"retired: merge3 ({V_v}+{Ba})"] = _timed(
        s_merge3, (v_hi_full, v_lo_full, ck_hi, ck_lo, acc0)
    )

    # -- stage: 1-lane winner-position sort (retired with the merge) ---
    def s_nfpos(i, a):
        pos, acc = a
        pos = pos.at[0].set(pos[0] ^ (i.astype(jnp.uint32) & 1))
        (pos2,) = lax.sort((pos,), num_keys=1)
        acc = acc.at[0].add(_fold(pos2))
        return pos, acc

    nf_pos = jnp.arange(M, dtype=jnp.uint32)
    results[f"retired: nfpos1 ({M})"] = _timed(s_nfpos, (nf_pos, acc0))

    # -- stage: fetch winners (round 5: packed gathers — payload mode
    # when the padded [Ba, W+3] fits the flat budget, else a packed
    # 4-lane meta gather + successor recompute; PERF.md §gathers) -----
    pay_fetch = (not chunked) and (Ba * 512 <= c.flat_budget_bytes)
    ebits_dummy = jnp.zeros(F_f, jnp.uint32)

    if pay_fetch:
        # Mirror the engine's packed payload (succ ++ keys ++ meta —
        # the one seam transpose back to rows at the gather staging);
        # profile the fetch at BOTH the max width and a typical
        # NF-class width (the engine's third ladder axis).
        succ_all = jax.jit(
            lambda fr: step_cols(
                pair_states(fr, pidx // jnp.uint32(EV)), pslot
            )[0].T
        )(frontier_f)
        pay = jnp.concatenate(
            [succ_all, ck_lo[:, None], ck_hi[:, None],
             ebits_dummy[pidx // jnp.uint32(EV)][:, None]],
            axis=1,
        )

        for NF_c in sorted({min(F, Ba), min(131072, Ba)}, reverse=True):
            nf_row = jnp.arange(NF_c, dtype=jnp.uint32) % jnp.uint32(Ba)

            def s_fetch(i, a):
                py, nf, acc = a
                nf = (nf + i.astype(jnp.uint32)) % jnp.uint32(Ba)
                p = py[nf]
                acc = acc.at[0].add(_fold(p))
                return py, nf, acc

            results[f"fetch ({NF_c} winners, payload)"] = _timed(
                s_fetch, (pay, nf_row, acc0)
            )
    else:
        def s_fetch(i, a):
            fr, nf, acc = a
            nf = (nf + i.astype(jnp.uint32)) % jnp.uint32(Ba)
            par_row = pidx[nf] // jnp.uint32(EV)
            succ_w_t, _, _ = step_cols(
                pair_states(fr, par_row), pslot[nf]
            )
            q = ebits_dummy[par_row]
            acc = acc.at[0].add(_fold(succ_w_t) + _fold(q))
            return fr, nf, acc

        nf_row = jnp.arange(min(F, Ba), dtype=jnp.uint32) % jnp.uint32(Ba)
        results[f"fetch ({min(F, Ba)} winners, recompute)"] = _timed(
            s_fetch, (frontier_f, nf_row, acc0)
        )

    print(f"\n{'stage':42s} {'ms/wave':>9s}  (baseline-subtracted)")
    total = 0.0
    for k, v in results.items():
        print(f"  {k:40s} {v:9.2f}")
        if not k.startswith("retired:"):
            # the retired rebuild-path rows are the A/B record, not
            # part of the running wave — keep them out of the
            # out-of-stage wall arithmetic
            total += v
    print(f"  {'SUM (stage compute)':40s} {total:9.2f}")
    return c, total


def wave_wall(kind, n, caps, target, optimize=True):
    """--wave-wall: the out-of-stage attribution (VERDICT r5 items
    1-2). Runs the stage profile for the in-stage sum, then re-times
    ONE full wave body on the same captured carry and attributes the
    compiled one-wave program per HLO category
    (stateright_tpu/wavewall.py)."""
    from stateright_tpu.wavewall import format_report, wave_wall_report

    c, stage_sum = stage_profile(kind, n, caps, target)
    print(f"\n## wave-wall profile: {kind} {n}")
    rep = wave_wall_report(c)
    print(format_report(rep, stage_sum_ms=stage_sum))


def wave_profile(kind, n, caps, optimize=True):
    from stateright_tpu.report import Reporter

    rows = []

    class Rec(Reporter):
        def __init__(self):
            self.last = time.monotonic()

        def delay(self):
            return 0.0

        def report_checking(self, data):
            now = time.monotonic()
            rows.append(
                (now - self.last, data.unique_states, data.max_depth)
            )
            self.last = now

    _spawn(kind, n, caps, optimize=optimize).join()  # warm compile at the same shapes? (no:
    # waves_per_sync differs; still warms the persistent XLA cache)
    c2 = _spawn(kind, n, caps, waves_per_sync=1, optimize=optimize)
    rec = Rec()
    t0 = time.monotonic()
    c2._ensure_run(rec)
    total = time.monotonic() - t0
    rows.append((time.monotonic() - rec.last, c2.unique_state_count(),
                 c2.max_depth()))
    print(f"\n## wave profile: {kind} {n} (total {total:.3f}s incl "
          f"per-wave sync, unique={c2.unique_state_count()})")
    prev = 0
    for i, (dt, u, d) in enumerate(rows):
        print(f"  wave {i:3d}: {dt*1000:8.1f} ms  new={u-prev:8d}  "
              f"unique={u:9d} depth={d}")
        prev = u


def micro():
    """--micro: primitive microbench at engine row counts on
    SYNTHETIC keys (folded in from the retired
    tools/profile_sortmerge.py, round 14 — its sort#1/2/3 labels
    timed the per-wave visited re-sort the round-10 streaming merge
    killed). These rows price the CURRENT stage seams' primitives
    without engine data: the B-row 3-lane candidate sort, the
    streaming binary-search membership into the sorted visited
    prefix, the O(V + NF) linear merge append, and the winner-fetch
    row gather. ``stage_profile`` times the same seams on REAL
    mid-run data; use this one to separate primitive cost from
    data-shape effects."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from stateright_tpu.ops.merge import member_sorted, merge_sorted

    key = jax.random.PRNGKey(0)

    def rnd(shape, i=0):
        return jax.random.bits(jax.random.fold_in(key, i), shape,
                               dtype=jnp.uint32)

    V, B, NF, W = 1 << 21, 1 << 20, 1 << 19, 19
    acc0 = jnp.zeros(1, jnp.uint32)
    # 2-limb sorted visited prefix (the (hi, lo) key order the
    # engines keep)
    v_hi, v_lo = jax.jit(
        lambda h, l: lax.sort((h, l), num_keys=2)
    )(rnd((V,), 1), rnd((V,), 2))
    b_hi, b_lo = rnd((B,), 3), rnd((B,), 4)
    s_hi, s_lo = jax.jit(
        lambda h, l: lax.sort((h, l), num_keys=2)
    )(b_hi, b_lo)
    w_hi, w_lo = s_hi[:NF], s_lo[:NF]
    print(f"\n## primitive microbench (V={V}, B={B}, NF={NF}, "
          f"per-op ms, in-loop amortized over {REPS} reps)")
    rows = {}

    def s_csort(i, a):
        kh, kl, acc = a
        kh = kh.at[0].set(kh[0] ^ (i.astype(jnp.uint32) & 1))
        pos = jnp.arange(1, B + 1, dtype=jnp.uint32)
        o_hi, o_lo, o_pos = lax.sort((kh, kl, pos), num_keys=2)
        acc = acc.at[0].add(_fold(o_hi) + _fold(o_lo) + _fold(o_pos))
        return kh, kl, acc

    rows[f"cand sort3 (B={B})"] = _timed(s_csort, (b_hi, b_lo, acc0))

    def s_member(i, a):
        vh, vl, qh, ql, acc = a
        vl = vl.at[0].set(vl[0] ^ (i.astype(jnp.uint32) & 1))
        m = member_sorted(vl, vh, ql, qh, impl="xla")
        acc = acc.at[0].add(_fold(m))
        return vh, vl, qh, ql, acc

    rows[f"member binsearch (V={V} | B={B})"] = _timed(
        s_member, (v_hi, v_lo, s_hi, s_lo, acc0)
    )

    def s_append(i, a):
        vh, vl, wh, wl, acc = a
        vl = vl.at[0].set(vl[0] ^ (i.astype(jnp.uint32) & 1))
        m_lo, m_hi = merge_sorted(vl, vh, wl, wh, impl="xla")
        acc = acc.at[0].add(_fold(m_lo) + _fold(m_hi))
        return vh, vl, wh, wl, acc

    rows[f"linear merge (V={V}+{NF})"] = _timed(
        s_append, (v_hi, v_lo, w_hi, w_lo, acc0)
    )

    pay = rnd((B, W), 5)
    idx = jnp.arange(NF, dtype=jnp.uint32) % jnp.uint32(B)

    def s_gather(i, a):
        py, nf, acc = a
        nf = (nf + i.astype(jnp.uint32)) % jnp.uint32(B)
        acc = acc.at[0].add(_fold(py[nf]))
        return py, nf, acc

    rows[f"fetch gather ({NF} rows W={W} from {B})"] = _timed(
        s_gather, (pay, idx, acc0)
    )

    for k, v in rows.items():
        print(f"  {k:44s} {v:9.2f} ms")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--paxos", type=int)
    ap.add_argument("--twopc", type=int)
    ap.add_argument(
        "--twopc-compiled", type=int,
        help="compiled 2pc system lane (two_phase_sys_compiled_encoded)"
        " at rm=N — the round-23 parity lane; pair with "
        "--no-optimize for the codegen-ablation denominator",
    )
    ap.add_argument(
        "--paxos-compiled", type=int,
        help="compiled paxos lane at N clients (reachable-mode "
        "harvest runs once before the profiled stages)",
    )
    ap.add_argument(
        "--no-optimize", action="store_true",
        help="compile the *-compiled lanes with optimize=False (the "
        "naive per-action codegen) — the per-stage A/B denominator "
        "for PERF.md §compiled-parity",
    )
    ap.add_argument("--target", type=int)
    ap.add_argument("--wave-profile", action="store_true")
    ap.add_argument("--wave-wall", action="store_true")
    ap.add_argument(
        "--micro", action="store_true",
        help="primitive microbench at engine row counts on synthetic "
        "keys (no model needed; the retired profile_sortmerge.py's "
        "successor)",
    )
    ap.add_argument(
        "--trace", nargs="?", const="default",
        choices=("default", "deep"), default=None,
        help="record run telemetry for the profiled engine runs and "
        "write TRACE_r*.jsonl + TRACE_r*.trace.json artifacts "
        "(stateright_tpu/telemetry.py)",
    )
    args = ap.parse_args()

    import jax

    print(f"backend: {jax.devices()}")

    if args.micro:
        micro()
        return

    # Structural sizes from the one shared table (capacity from the
    # pinned state counts, frontier from measured wave peaks);
    # per-wave BUDGETS are auto-sized — TUNED_ENGINE_CAPS and the
    # per-lane caps tables are gone (VERDICT r5 item 6).
    if args.paxos:
        from stateright_tpu.models.paxos_tpu import STRUCTURAL_SIZES

        caps = dict(STRUCTURAL_SIZES[args.paxos])
        caps["cand_capacity"] = "auto"
        kind, n = "paxos", args.paxos
        default_target = {3: 600_000, 4: 1_200_000, 5: 2_400_000}.get(
            args.paxos, 1_000_000
        )
    elif args.twopc:
        kind, n = "twopc", args.twopc
        caps = {
            8: dict(capacity=1 << 21, frontier_capacity=1 << 19,
                    cand_capacity="auto"),
            9: dict(capacity=11 << 20, frontier_capacity=3 << 19,
                    cand_capacity="auto", tile_rows=1 << 20),
        }[n]
        default_target = {8: 900_000, 9: 5_000_000}[n]
    elif args.twopc_compiled:
        kind, n = "twopc-compiled", args.twopc_compiled
        # The bench parity-lane shapes (identical to the hand "2pc
        # rm=N" lanes — the space is count-identical, so the wave
        # peaks are too); other rm counts fall back to the same
        # ~2.53 bits/RM growth the hand lanes follow.
        import math

        bench_caps = {
            5: dict(capacity=1 << 14, frontier_capacity=1 << 11),
            6: dict(capacity=1 << 16, frontier_capacity=1 << 14),
            7: dict(capacity=1 << 19, frontier_capacity=1 << 16),
        }
        if n in bench_caps:
            caps = dict(bench_caps[n], cand_capacity="auto")
        else:
            cap = 1 << max(10, math.ceil(2.6 * n + 1.5))
            caps = dict(capacity=cap,
                        frontier_capacity=max(256, cap // 4),
                        cand_capacity="auto")
        default_target = {5: 4_000, 6: 25_000, 7: 150_000}.get(
            n, max(512, caps["capacity"] // 4)
        )
    elif args.paxos_compiled:
        kind, n = "paxos-compiled", args.paxos_compiled
        caps = dict(capacity=1 << 15, frontier_capacity=1 << 12,
                    cand_capacity="auto")
        default_target = 8_000
    else:
        raise SystemExit(
            "pass --paxos N, --twopc N, --twopc-compiled N or "
            "--paxos-compiled N"
        )
    spawn_kw = (
        {"optimize": False} if args.no_optimize else {}
    )
    if args.no_optimize and not kind.endswith("compiled"):
        raise SystemExit("--no-optimize only applies to the "
                         "*-compiled lanes")

    def dispatch():
        if args.wave_profile:
            wave_profile(kind, n, caps, **spawn_kw)
        elif args.wave_wall:
            wave_wall(kind, n, caps, args.target or default_target,
                      **spawn_kw)
        else:
            stage_profile(kind, n, caps,
                          args.target or default_target, **spawn_kw)

    if args.trace is None:
        dispatch()
        return
    import sys as _sys

    _sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from stateright_tpu.telemetry import RunTracer, write_artifacts

    tracer = RunTracer(level=args.trace)
    try:
        with tracer.activate():
            dispatch()
    finally:
        # a failed/interrupted profile's partial trace still lands
        if tracer.events:
            jsonl, chrome = write_artifacts(tracer)
            print(f"trace: wrote {jsonl} + {chrome}")


if __name__ == "__main__":
    main()
