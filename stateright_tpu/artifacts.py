"""Run-artifact conventions shared by every tool that writes one.

The repo root accumulates numbered round artifacts — ``BENCH_r*.json``
(driver-captured bench output), ``LINT_r*.json`` (kernel-lint,
tools/lint_kernels.py), ``MULTICHIP_r*.json`` (sharded dryrun), and
``TRACE_r*.jsonl`` / ``TRACE_r*.trace.json`` (run telemetry,
stateright_tpu/telemetry.py). They form ONE round sequence: a perf
round points at "lint clean at r07, trace at r07" the way it points at
its bench lane, so every writer numbers past the highest round of ANY
family. This module is the single home for that numbering (the lint
CLI and the trace exporter used to risk growing private copies) and
for the provenance block every artifact embeds — the "number with no
context" fix: a count or a wall time is only comparable across rounds
when the artifact names the jax/jaxlib versions, device, platform,
git SHA, and lane config it was measured under.

``MEM_r*.json`` (memory summaries, tools/mem_report.py /
memplan.write_memory_artifact) numbers through the same helpers but
in its OWN sequence (``next_round(root, stems=("MEM",))`` —
``MEM_r01`` first): a MEM artifact is *derived from* a TRACE and
names it in its ``trace`` field, so the cross-reference — not a
shared counter — pairs it with a perf round. ``COMM_r*.json``
(comms-lint, tools/lint_comms.py) follows the same own-sequence
pattern: a COMM artifact is the static communication contract at one
commit, cross-referenced BY bench/lint artifacts
(:func:`latest_comms_summary`) rather than sharing their counter.
``LAT_r*.json`` (latency summaries, tools/latency_report.py /
telemetry.write_latency_artifact) follows MEM's pattern exactly:
derived from a TRACE, names it in its ``trace`` field, numbers in
its own sequence (``next_round(root, stems=("LAT",))``).
``SERVE_r*.json`` (resident-service session reports,
tools/serve_report.py over a service trace — stateright_tpu/serve.py)
follows the same derived-from-a-TRACE pattern: own sequence
(``SERVE_r01`` first), cross-referenced BY bench provenance via
:func:`latest_serve_summary`.
``SOUND_r*.json`` (reduction soundness certificates,
``stateright_tpu analyze soundness`` — analysis/soundness.py)
follows COMM's own-sequence pattern: the certificate is the static
proof state of every declared reduction spec at one commit,
consulted at spawn by the engine gates and cross-referenced BY bench
``(sym)`` lane detail via :func:`latest_soundness_summary`.
``SLO_r*.json`` (service-level-objective gate evaluations,
stateright_tpu/metrics.py ``write_slo_artifact`` via
tools/slo_report.py or the sustained tools/serve_loadtest.py run)
follows the same own-sequence pattern: one declarative-spec
evaluation over a load test or rollup, cross-referenced BY bench
provenance via :func:`latest_slo_summary`.
"""

from __future__ import annotations

import glob
import json
import os
import re
import subprocess
import sys

#: every artifact family that participates in the shared round
#: numbering (stem of ``<STEM>_rNN.<ext>``).
ARTIFACT_STEMS = ("BENCH", "LINT", "MULTICHIP", "TRACE")


def repo_root() -> str:
    """The repo root this package sits in (artifacts land beside
    ROADMAP.md / BENCH_r*.json)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def next_round(root: str | None = None,
               stems: tuple = ARTIFACT_STEMS) -> int:
    """The next free round number: one past the highest ``_rNN`` of
    any listed artifact family (any extension)."""
    root = repo_root() if root is None else root
    best = 0
    for stem in stems:
        for p in glob.glob(os.path.join(root, f"{stem}_r*.*")):
            m = re.search(r"_r(\d+)\.", os.path.basename(p))
            if m:
                best = max(best, int(m.group(1)))
    return best + 1


def artifact_path(stem: str, ext: str = "json",
                  root: str | None = None,
                  round: int | None = None) -> str:
    """``<root>/<stem>_rNN.<ext>``, auto-numbered unless ``round`` is
    pinned (pin it to write a multi-file artifact pair — e.g. the
    trace exporter's ``TRACE_rNN.jsonl`` + ``TRACE_rNN.trace.json`` —
    into one round)."""
    root = repo_root() if root is None else root
    if round is None:
        round = next_round(root)
    return os.path.join(root, f"{stem}_r{round:02d}.{ext}")


def latest_artifact(stem: str, root: str | None = None) -> str | None:
    """Path of the highest-round artifact of one family (any
    extension), or None when the family has no artifacts yet. A round
    can hold several files (the trace exporter's ``TRACE_rNN.jsonl``
    + ``TRACE_rNN.trace.json`` pair); ties break to the
    lexicographically-first basename — deterministic across
    filesystems, and for the trace pair it picks the JSONL event log
    over the derived Chrome-trace export."""
    root = repo_root() if root is None else root
    best, path = -1, None
    for p in sorted(glob.glob(os.path.join(root, f"{stem}_r*.*")),
                    key=os.path.basename):
        m = re.search(r"_r(\d+)\.", os.path.basename(p))
        if m and int(m.group(1)) > best:
            best, path = int(m.group(1)), p
    return path


def latest_lint_summary(root: str | None = None) -> dict | None:
    """Cross-reference block for the newest ``LINT_r*.json``: the
    artifact name plus its wave-body ``carry-copy-bytes`` totals (the
    gated switch-carry metric, analysis/rules.py). bench.py embeds
    this in every lane's provenance so a BENCH number and the LINT
    round it was measured against pair up without hand-matching round
    numbers. Best effort: None when no artifact exists or it predates
    the estimator."""
    path = latest_artifact("LINT", root)
    if path is None:
        return None
    # Best effort means structurally too: a hand-edited or truncated
    # artifact (null data block, string byte counts, findings not a
    # list) must degrade to None, not abort bench.py at startup.
    try:
        with open(path) as fh:
            report = json.load(fh)
        # Keyed per fixture (encoding), wave-body path only: the
        # number CARRY_COPY_BYTE_BUDGETS prices is per-fixture, so a
        # future second wave-body fixture must not silently turn the
        # scalar into a cross-fixture sum.
        per_fix: dict = {}
        for f in report.get("findings", ()):
            if (f.get("rule") == "carry-copy-bytes"
                    and f.get("severity") == "info"
                    and f.get("path") == "wave-body"):
                data = f.get("data")
                if not isinstance(data, dict):
                    # a stripped data block is "predates the
                    # estimator", not "measured zero bytes"
                    continue
                name = str(f.get("encoding"))
                c, m = per_fix.get(name, (0, 0))
                per_fix[name] = (
                    c + int(data.get("switch_carry_bytes", 0)),
                    m + int(data.get("branch_move_bytes", 0)),
                )
        # Surface the SHA the lint artifact was produced AT — a
        # consumer (or reader of the BENCH artifact) can then see at
        # a glance whether the static numbers match the benched
        # commit, which is the hand-matching this block exists to
        # eliminate. Guarded like the findings walk: a mangled
        # provenance field degrades, never aborts.
        prov = report.get("provenance")
        lint_sha = (prov.get("git_sha")
                    if isinstance(prov, dict) else None)
    except (OSError, ValueError, TypeError, AttributeError, KeyError):
        return None
    if not per_fix:
        return None
    # The HEAD to compare against is the checkout the artifact lives
    # in (the root argument), not necessarily this package's repo —
    # and an unanswerable HEAD (no git) means "unknown", not False.
    # A DIRTY tree also means "unknown": the artifact may have been
    # measured on uncommitted code HEAD says nothing about, so a
    # bare sha match would claim a pairing the commit can't back.
    repo = repo_root() if root is None else root
    head = _git_sha(repo)
    dirty = _git_dirty(repo)
    out = {
        "artifact": os.path.basename(path),
        "clean": bool(report.get("clean")),
        "git_sha": lint_sha,
        "sha_matches_head": (
            lint_sha == head
            if lint_sha is not None and head is not None
            and dirty is False
            else None
        ),
    }
    if len(per_fix) == 1:
        ((carry, move),) = per_fix.values()
        out["carry_copy_bytes"] = carry
        out["branch_move_bytes"] = move
    else:
        # ambiguous as a scalar — expose the per-fixture breakdown
        # instead of a sum no budget entry corresponds to
        out["carry_copy_bytes"] = None
        out["branch_move_bytes"] = None
        out["fixtures"] = {
            name: {"carry_copy_bytes": c, "branch_move_bytes": m}
            for name, (c, m) in sorted(per_fix.items())
        }
    return out


def latest_comms_summary(root: str | None = None) -> dict | None:
    """Cross-reference block for the newest ``COMM_r*.json``
    (comms-lint, tools/lint_comms.py): artifact name, clean flag, the
    producing SHA, and the per-fixture collective accounting the
    static-vs-runtime reconciliation reads (per-wave peak bytes +
    all_to_all row bytes, telemetry.shard_balance ``comms_static``).
    bench.py and lint_kernels.py embed this beside the LINT
    cross-reference (the PR 5 ``latest_lint_summary`` pattern). Best
    effort with the same guarantees: a missing, hand-edited, or
    truncated artifact degrades to None, never aborts the caller."""
    path = latest_artifact("COMM", root)
    if path is None:
        return None
    try:
        with open(path) as fh:
            report = json.load(fh)
        comms = report.get("comms")
        if not isinstance(comms, dict) or not comms:
            return None
        fixtures: dict = {}
        for name, c in comms.items():
            if not isinstance(c, dict):
                continue
            fixtures[str(name)] = {
                "per_wave_peak_bytes": (
                    int(c["per_wave_peak_bytes"])
                    if "per_wave_peak_bytes" in c else None
                ),
                "all_to_all_row_bytes": (
                    int(c["all_to_all_row_bytes"])
                    if "all_to_all_row_bytes" in c else None
                ),
            }
        prov = report.get("provenance")
        comm_sha = (prov.get("git_sha")
                    if isinstance(prov, dict) else None)
    except (OSError, ValueError, TypeError, AttributeError, KeyError):
        return None
    if not fixtures:
        return None
    repo = repo_root() if root is None else root
    head = _git_sha(repo)
    dirty = _git_dirty(repo)
    return {
        "artifact": os.path.basename(path),
        "clean": bool(report.get("clean")),
        "git_sha": comm_sha,
        "sha_matches_head": (
            comm_sha == head
            if comm_sha is not None and head is not None
            and dirty is False
            else None
        ),
        "fixtures": dict(sorted(fixtures.items())),
    }


def latest_soundness_summary(root: str | None = None) -> dict | None:
    """Cross-reference block for the newest ``SOUND_r*.json``
    (reduction soundness certificates, analysis/soundness.py):
    artifact name, clean flag (every checked spec certified), the
    producing SHA, and the per-spec status map. Best effort with the
    :func:`latest_lint_summary` guarantees: a missing, hand-edited,
    or truncated artifact degrades to None, never aborts the
    caller."""
    path = latest_artifact("SOUND", root)
    if path is None:
        return None
    try:
        with open(path) as fh:
            report = json.load(fh)
        specs_block = report.get("specs")
        if not isinstance(specs_block, dict) or not specs_block:
            return None
        specs = {
            str(name): str(s["status"])
            for name, s in specs_block.items()
            if isinstance(s, dict) and "status" in s
        }
        prov = report.get("provenance")
        sound_sha = (prov.get("git_sha")
                     if isinstance(prov, dict) else None)
    except (OSError, ValueError, TypeError, AttributeError, KeyError):
        return None
    if not specs:
        return None
    repo = repo_root() if root is None else root
    head = _git_sha(repo)
    dirty = _git_dirty(repo)
    return {
        "artifact": os.path.basename(path),
        "clean": bool(report.get("clean")),
        "git_sha": sound_sha,
        "sha_matches_head": (
            sound_sha == head
            if sound_sha is not None and head is not None
            and dirty is False
            else None
        ),
        "specs": dict(sorted(specs.items())),
    }


def latest_ckpt_summary(root: str | None = None) -> dict | None:
    """Cross-reference block for the newest ``CKPT_r*.json``
    (crash-matrix, tools/crash_matrix.py): artifact name, clean flag
    (every matrix cell landed on recover-or-refuse-loudly), the
    producing SHA, and the per-cell verdicts. bench.py embeds this
    beside the LINT/COMM cross-references. Best effort with the same
    guarantees: a missing, hand-edited, or truncated artifact
    degrades to None, never aborts the caller."""
    path = latest_artifact("CKPT", root)
    if path is None:
        return None
    try:
        with open(path) as fh:
            report = json.load(fh)
        cells = report.get("cells")
        if not isinstance(cells, dict) or not cells:
            return None
        cell_verdicts = {
            str(name): str(c.get("verdict"))
            for name, c in cells.items() if isinstance(c, dict)
        }
        prov = report.get("provenance")
        ckpt_sha = (prov.get("git_sha")
                    if isinstance(prov, dict) else None)
    except (OSError, ValueError, TypeError, AttributeError, KeyError):
        return None
    if not cell_verdicts:
        return None
    repo = repo_root() if root is None else root
    head = _git_sha(repo)
    dirty = _git_dirty(repo)
    out = {
        "artifact": os.path.basename(path),
        "clean": bool(report.get("clean")),
        "git_sha": ckpt_sha,
        "sha_matches_head": (
            ckpt_sha == head
            if ckpt_sha is not None and head is not None
            and dirty is False
            else None
        ),
        "cells": dict(sorted(cell_verdicts.items())),
    }
    # the degrade column (the degrade-and-continue round): which
    # cells landed on continue-degraded, with their old -> new shard
    # counts — bench provenance embeds these beside LINT/COMM
    deg = report.get("degrade_cells")
    if isinstance(deg, dict) and deg:
        out["degrade_cells"] = {
            str(k): v for k, v in sorted(deg.items())
            if isinstance(v, dict)
        }
    return out


def latest_serve_summary(root: str | None = None) -> dict | None:
    """Cross-reference block for the newest ``SERVE_r*.json``
    (resident-service session report, tools/serve_report.py): artifact
    name, the producing SHA, session count, and the warm-vs-cold
    latency-per-query verdict (cold first-query vs warm repeat-query
    time-to-verdict with the compile-tier attribution) — ROADMAP
    direction 4's headline numbers, embedded in bench provenance
    beside the LINT/COMM/CKPT blocks. Best effort with the same
    guarantees: a missing, hand-edited, or truncated artifact degrades
    to None, never aborts the caller."""
    path = latest_artifact("SERVE", root)
    if path is None:
        return None
    try:
        with open(path) as fh:
            report = json.load(fh)
        sessions = report.get("sessions")
        if not isinstance(sessions, list) or not sessions:
            return None
        prov = report.get("provenance")
        serve_sha = (prov.get("git_sha")
                     if isinstance(prov, dict) else None)
        wvc = report.get("warm_vs_cold")
        warm_block = None
        if isinstance(wvc, list) and wvc:
            wvc = wvc[0]
        if isinstance(wvc, dict):
            warm_block = {
                k: wvc.get(k)
                for k in ("cold_ttv_sec", "warm_ttv_sec",
                          "ttv_delta_sec", "compile_delta_sec",
                          "dispatch_net_delta_sec", "warm_start")
            }
        batch_block = None
        lt = report.get("loadtest")
        if isinstance(lt, dict):
            # the wave-batching A/B headline (tools/serve_loadtest.py
            # — batched per-query dispatch+sync overhead vs the
            # FIFO-serial baseline at identical counts)
            batch_block = {
                k: lt.get(k)
                for k in ("clients", "lane", "amortization_x",
                          "batched_per_query_overhead_sec",
                          "fifo_per_query_overhead_sec")
            }
    except (OSError, ValueError, TypeError, AttributeError, KeyError):
        return None
    repo = repo_root() if root is None else root
    head = _git_sha(repo)
    dirty = _git_dirty(repo)
    return {
        "artifact": os.path.basename(path),
        "git_sha": serve_sha,
        "sha_matches_head": (
            serve_sha == head
            if serve_sha is not None and head is not None
            and dirty is False
            else None
        ),
        "sessions": len(sessions),
        "warm_vs_cold": warm_block,
        "batching": batch_block,
    }


def latest_slo_summary(root: str | None = None) -> dict | None:
    """Cross-reference block for the newest ``SLO_r*.json`` (the
    declarative service-level-objective gate evaluation,
    stateright_tpu/metrics.py evaluate_slo via tools/slo_report.py or
    the sustained serve_loadtest): artifact name, the producing SHA,
    the overall verdict, and per-objective status — the direction-2(c)
    signal-plane evidence, embedded in bench provenance beside the
    LINT/COMM/CKPT/SERVE blocks. Best effort with the same
    guarantees: a missing, hand-edited, or truncated artifact degrades
    to None, never aborts the caller."""
    path = latest_artifact("SLO", root)
    if path is None:
        return None
    try:
        with open(path) as fh:
            report = json.load(fh)
        evaluation = report.get("evaluation")
        if not isinstance(evaluation, dict):
            return None
        objectives = {
            o["objective"]: o["status"]
            for o in evaluation.get("objectives") or []
            if isinstance(o, dict)
        }
        prov = report.get("provenance")
        slo_sha = (prov.get("git_sha")
                   if isinstance(prov, dict) else None)
    except (OSError, ValueError, TypeError, AttributeError, KeyError):
        return None
    repo = repo_root() if root is None else root
    head = _git_sha(repo)
    dirty = _git_dirty(repo)
    return {
        "artifact": os.path.basename(path),
        "git_sha": slo_sha,
        "sha_matches_head": (
            slo_sha == head
            if slo_sha is not None and head is not None
            and dirty is False
            else None
        ),
        "ok": bool(evaluation.get("ok")),
        "objectives": dict(sorted(objectives.items())),
    }


def _git_dirty(root: str) -> bool | None:
    """True when the working tree has uncommitted changes, False when
    clean, None when git can't answer."""
    try:
        out = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=root, capture_output=True, text=True, timeout=10,
        )
        if out.returncode == 0:
            return bool(out.stdout.strip())
    except (OSError, subprocess.SubprocessError):
        pass
    return None


def _git_sha(root: str) -> str | None:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=10,
        )
        if out.returncode == 0:
            return out.stdout.strip()
    except (OSError, subprocess.SubprocessError):
        pass
    return None


def provenance(lane: dict | None = None) -> dict:
    """The context block embedded in every artifact: toolchain
    versions, the device the numbers were measured on, the git SHA of
    the code that produced them, and the exact lane config. Best
    effort — a field the environment can't answer is None, never a
    raise (artifacts must still be writable from a stripped
    container)."""
    out: dict = {
        "python": sys.version.split()[0],
        "jax": None,
        "jaxlib": None,
        "backend": None,
        "device_kind": None,
        "device_count": None,
        "platform_version": None,
        "git_sha": _git_sha(repo_root()),
    }
    try:
        import jax

        out["jax"] = jax.__version__
        try:
            import jaxlib

            out["jaxlib"] = jaxlib.__version__
        except (ImportError, AttributeError):
            pass
        devices = jax.devices()
        out["backend"] = jax.default_backend()
        out["device_kind"] = devices[0].device_kind if devices else None
        out["device_count"] = len(devices)
        try:
            out["platform_version"] = devices[0].client.platform_version
        except (AttributeError, IndexError):
            pass
    except Exception:  # jax not importable / no backend: still usable
        pass
    if lane is not None:
        out["lane"] = lane
    return out
