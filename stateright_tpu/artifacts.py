"""Run-artifact conventions shared by every tool that writes one.

The repo root accumulates numbered round artifacts — ``BENCH_r*.json``
(driver-captured bench output), ``LINT_r*.json`` (kernel-lint,
tools/lint_kernels.py), ``MULTICHIP_r*.json`` (sharded dryrun), and
``TRACE_r*.jsonl`` / ``TRACE_r*.trace.json`` (run telemetry,
stateright_tpu/telemetry.py). They form ONE round sequence: a perf
round points at "lint clean at r07, trace at r07" the way it points at
its bench lane, so every writer numbers past the highest round of ANY
family. This module is the single home for that numbering (the lint
CLI and the trace exporter used to risk growing private copies) and
for the provenance block every artifact embeds — the "number with no
context" fix: a count or a wall time is only comparable across rounds
when the artifact names the jax/jaxlib versions, device, platform,
git SHA, and lane config it was measured under.
"""

from __future__ import annotations

import glob
import os
import re
import subprocess
import sys

#: every artifact family that participates in the shared round
#: numbering (stem of ``<STEM>_rNN.<ext>``).
ARTIFACT_STEMS = ("BENCH", "LINT", "MULTICHIP", "TRACE")


def repo_root() -> str:
    """The repo root this package sits in (artifacts land beside
    ROADMAP.md / BENCH_r*.json)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def next_round(root: str | None = None,
               stems: tuple = ARTIFACT_STEMS) -> int:
    """The next free round number: one past the highest ``_rNN`` of
    any listed artifact family (any extension)."""
    root = repo_root() if root is None else root
    best = 0
    for stem in stems:
        for p in glob.glob(os.path.join(root, f"{stem}_r*.*")):
            m = re.search(r"_r(\d+)\.", os.path.basename(p))
            if m:
                best = max(best, int(m.group(1)))
    return best + 1


def artifact_path(stem: str, ext: str = "json",
                  root: str | None = None,
                  round: int | None = None) -> str:
    """``<root>/<stem>_rNN.<ext>``, auto-numbered unless ``round`` is
    pinned (pin it to write a multi-file artifact pair — e.g. the
    trace exporter's ``TRACE_rNN.jsonl`` + ``TRACE_rNN.trace.json`` —
    into one round)."""
    root = repo_root() if root is None else root
    if round is None:
        round = next_round(root)
    return os.path.join(root, f"{stem}_r{round:02d}.{ext}")


def _git_sha(root: str) -> str | None:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=10,
        )
        if out.returncode == 0:
            return out.stdout.strip()
    except (OSError, subprocess.SubprocessError):
        pass
    return None


def provenance(lane: dict | None = None) -> dict:
    """The context block embedded in every artifact: toolchain
    versions, the device the numbers were measured on, the git SHA of
    the code that produced them, and the exact lane config. Best
    effort — a field the environment can't answer is None, never a
    raise (artifacts must still be writable from a stripped
    container)."""
    out: dict = {
        "python": sys.version.split()[0],
        "jax": None,
        "jaxlib": None,
        "backend": None,
        "device_kind": None,
        "device_count": None,
        "platform_version": None,
        "git_sha": _git_sha(repo_root()),
    }
    try:
        import jax

        out["jax"] = jax.__version__
        try:
            import jaxlib

            out["jaxlib"] = jaxlib.__version__
        except (ImportError, AttributeError):
            pass
        devices = jax.devices()
        out["backend"] = jax.default_backend()
        out["device_kind"] = devices[0].device_kind if devices else None
        out["device_count"] = len(devices)
        try:
            out["platform_version"] = devices[0].client.platform_version
        except (AttributeError, IndexError):
            pass
    except Exception:  # jax not importable / no backend: still usable
        pass
    if lane is not None:
        out["lane"] = lane
    return out
