"""The resident multi-tenant checking service (ROADMAP direction 4).

Every check used to be a cold process: the ~106 ms sync floor, the
multi-second XLA compiles (TRACE_r21 measured a 17.9 s persistent-cache
disk fetch inside chunk 0), and the whole exploration were paid per
query. This module keeps ONE warm process resident on the device and
serves many concurrent sessions from it — the
checking-as-a-cloud-service framing of arXiv:1203.6806 and the
portable-caching / warm-start framing of arXiv:2603.09555:

* **Sessions** (:class:`CheckService.check`): each query — a CLI check
  lane (``paxos check-tpu 2``, same argv, bit-identical counts to a
  cold process) or an Explorer browse — runs as one :class:`Session`
  with its OWN :class:`~stateright_tpu.telemetry.RunTracer` installed
  thread-locally (``activate_thread``), so concurrent sessions trace
  into disjoint event streams with zero cross-session bleed. The
  service intercepts the checker at the CLI's one ``_report`` seam
  (cli.py) — the same seam the checkpoint/resume flags land on.
* **FIFO device queue** (:class:`FifoLock`): every engine chunk
  dispatch+sync acquires the service's gate (the ``dispatch_gate``
  seam in checkers/tpu.py, the one funnel both the untiered and
  tiered chunk loops pass through), so concurrent sessions interleave
  at chunk granularity in strict arrival order instead of racing the
  device. Per-session queue wait is accumulated and reported.
* **Admission** (:func:`~stateright_tpu.memplan.session_resident_bytes`):
  a device session's dominant resident bytes are priced from config
  alone and checked against the service's device budget BEFORE any
  program build or device work — an oversized query is refused loudly
  (:class:`AdmissionRefused`), not discovered mid-run as an OOM.
* **Compiled-program LRU**: the engines' ``_programs``/XLA chunk cache
  (checkers/tpu.py ``_CHUNK_CACHE``) grows one entry per distinct
  program key; the service bounds it by BYTES — each entry priced by
  the memplan ledger total of the session that used it — evicting
  least-recently-used entries past ``program_budget_bytes`` (a
  re-submitted evicted query recompiles, or re-fetches from the
  persistent XLA disk cache; counts are unaffected).
* **Incremental re-check / warm start**: a completed device session's
  final chunk carry is retained as a snapshot
  (:func:`~stateright_tpu.checkpoint.retain_final_snapshot`, keyed by
  :func:`~stateright_tpu.checkpoint.encoding_fingerprint`). A
  re-submitted model whose fingerprint matches resumes from the
  retained visited set through the existing checkpoint/restore seam —
  uploads, re-shards if the layout changed — and settles in one chunk
  with zero new waves, instead of re-exploring from wave 0. An edited
  model changes the fingerprint, the resume refuses, and the session
  runs cold: correctness never rides the cache.
* **HTTP surface**: the service mounts on the Explorer's server
  (explorer/server.py ``make_server(registry=...)``) — Explorer
  browse/``run_to_completion`` queries keep their per-request spans
  and checker lock, ``POST /.check`` runs a CLI session remotely
  (the ``stateright_tpu --connect`` client mode), ``GET
  /.serve/sessions`` lists sessions, ``POST /.serve/trace`` exports
  the merged trace.
* **Live metrics** (stateright_tpu/metrics.py): the service owns a
  :class:`~stateright_tpu.metrics.MetricsRegistry` — the serve seams
  the tracer never sees (admission accept/refuse with priced bytes,
  FIFO queue depth + queue wait, dispatch-gate hold, active sessions,
  warm/cold split, LRU/spool evictions) are metered directly, and
  every session's schema-validated telemetry feeds the registry
  through the tracer→metrics bridge at settle. ``GET /.metrics``
  serves Prometheus text, ``--metrics-interval=N`` appends JSONL
  rollups, and ``/.status`` carries a compact metrics block.
* **Reporting**: :meth:`CheckService.write_trace` merges every
  session's events into one TRACE artifact (one run index per
  session, ``session_begin``/``session_end``/``program_evict``
  service events); :func:`serve_summary` derives the per-session
  time-to-verdict / queue-wait / compile-tier / cache-hit tables
  tools/serve_report.py renders into auto-numbered ``SERVE_r*.json``.
"""

from __future__ import annotations

import hashlib
import io
import itertools
import json
import os
import sys
import tempfile
import threading
import time
from collections import OrderedDict, deque
from contextlib import nullcontext
from typing import Optional

from . import checkpoint, memplan, telemetry
from .metrics import MetricsRegistry, Rollup, bridge_events


class AdmissionRefused(RuntimeError):
    """The session's projected resident bytes exceed the service's
    device budget — refused BEFORE any program build or device work."""


#: session argv must be plain lane argv: the runtime flags would
#: fight the service's own machinery — --trace wants the process
#: tracer the per-session tracers replace, --checkpoint/--resume
#: would race the warm-start retention on the same engine seams —
#: so telemetry and durability are the SERVICE's job, refused loudly.
#: (--symmetry/--ample-set/--unsound-ok are runtime flags too, so a
#: session can never smuggle an uncertified reduction past the
#: soundness-certificate gate, analysis/soundness.py: any reduction a
#: service checker runs was armed in-process through CheckerBuilder,
#: where the spawn gate fires — both refusal families format through
#: checkers/common.reduction_refusal, so service sessions and CLI
#: runs print identical text.)
_FLAG_REFUSAL = (
    "service sessions take plain lane argv (e.g. ['paxos', "
    "'check-tpu', '2']); runtime flags are process-global and are "
    "the service's job — telemetry via the per-session tracer / "
    "write_trace(), durability via warm-start retention"
)


# -- FIFO device queue ----------------------------------------------------


class FifoLock:
    """A FIFO-fair mutex: acquirers are served strictly in arrival
    order. ``threading.Lock`` makes no fairness promise — under
    contention one session could starve while another hogs the device
    — and the service's latency-per-query story needs queue wait to be
    arrival-ordered and therefore boundable. Release HANDS OFF to the
    oldest waiter (the lock never goes briefly free for a newcomer to
    steal)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._waiters: deque = deque()
        self._locked = False

    def acquire(self) -> None:
        with self._mu:
            if not self._locked and not self._waiters:
                self._locked = True
                return
            ev = threading.Event()
            self._waiters.append(ev)
        ev.wait()

    def release(self) -> None:
        with self._mu:
            if self._waiters:
                self._waiters.popleft().set()  # hand-off: stays locked
            else:
                self._locked = False

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _GateHandle:
    """The per-session view of the service's device gate, installed as
    the engine's ``dispatch_gate``: acquiring accumulates this
    session's queue wait (the latency-per-query lane serve_report
    prints), releasing hands the device to the next queued session."""

    __slots__ = ("_gate", "_session", "_m", "_t_acq")

    def __init__(self, gate: FifoLock, session: "Session",
                 m: Optional[dict] = None):
        self._gate = gate
        self._session = session
        self._m = m
        self._t_acq = 0.0

    def __enter__(self):
        m = self._m
        if m is not None:
            m["queue_depth"].inc()
        t0 = time.monotonic()
        self._gate.acquire()
        t1 = time.monotonic()
        self._session.gate_wait_sec += t1 - t0
        if m is not None:
            m["queue_depth"].dec()
            m["queue_wait"].observe(t1 - t0)
            self._t_acq = t1
        return self

    def __exit__(self, *exc):
        self._gate.release()
        if self._m is not None:
            self._m["gate_hold"].observe(
                time.monotonic() - self._t_acq
            )
        return False


class _FusedGateHandle:
    """The fused-dispatch view of the device gate: one acquire per
    fused chunk advances N sessions at once, so the queue wait is
    attributed to every member session as a 1/N share — the same
    amortization the latency profile applies to the sync floor."""

    __slots__ = ("_gate", "_sessions", "_m", "_t_acq")

    def __init__(self, gate: FifoLock, sessions: list,
                 m: Optional[dict] = None):
        self._gate = gate
        self._sessions = sessions
        self._m = m
        self._t_acq = 0.0

    def __enter__(self):
        m = self._m
        if m is not None:
            m["queue_depth"].inc()
        t0 = time.monotonic()
        self._gate.acquire()
        t1 = time.monotonic()
        share = (t1 - t0) / max(1, len(self._sessions))
        for s in self._sessions:
            s.gate_wait_sec += share
        if m is not None:
            m["queue_depth"].dec()
            m["queue_wait"].observe(t1 - t0)
            self._t_acq = t1
        return self

    def __exit__(self, *exc):
        self._gate.release()
        if self._m is not None:
            self._m["gate_hold"].observe(
                time.monotonic() - self._t_acq
            )
        return False


# -- per-thread stdout capture --------------------------------------------


class _ThreadLocalStdout:
    """A ``sys.stdout`` proxy with a per-thread target: the service
    captures each session's handler output (the CLI lanes print their
    reference-format report to stdout) WITHOUT redirecting other
    threads — ``contextlib.redirect_stdout`` swaps the process-global
    stream and would bleed concurrent sessions into each other.
    Threads with no target installed write through to the real
    stream untouched."""

    def __init__(self, real):
        self._real = real
        self._tls = threading.local()

    def push(self, target) -> None:
        self._tls.target = target

    def pop(self) -> None:
        self._tls.target = None

    def _target(self):
        return getattr(self._tls, "target", None) or self._real

    def write(self, s):
        return self._target().write(s)

    def flush(self):
        t = self._target()
        flush = getattr(t, "flush", None)
        if flush is not None:
            flush()

    def __getattr__(self, name):
        return getattr(self._real, name)


def _stdout_proxy() -> _ThreadLocalStdout:
    """Install (idempotently) the thread-local stdout proxy over the
    CURRENT ``sys.stdout`` — re-wrapping whatever stream a test
    harness may have installed since the last call."""
    cur = sys.stdout
    if isinstance(cur, _ThreadLocalStdout):
        return cur
    proxy = _ThreadLocalStdout(cur)
    sys.stdout = proxy
    return proxy


# -- sessions -------------------------------------------------------------


class Session:
    """One query's lifecycle record: identity, lane argv, state
    machine (queued → running → done/failed/refused; Explorer mounts
    stay ``serving``), the per-session tracer, timing lanes (admission
    wait, accumulated device-queue wait, wall), outcome counts, and
    the byte/cache attribution (admitted bytes, program key,
    evictions this session triggered)."""

    def __init__(self, sid: int, kind: str, argv):
        self.id = sid
        self.kind = kind
        self.argv = tuple(argv)
        self.state = "queued"
        self.error: Optional[str] = None
        self.output: Optional[str] = None
        self.tracer = None
        self.checker = None
        self.device = False
        self.running = False
        self.warm_start = False
        self.encoding_fp: Optional[str] = None
        self.program_key: Optional[str] = None
        self.admitted_bytes: Optional[int] = None
        self.plan_bytes: Optional[int] = None
        self.unique: Optional[int] = None
        self.total: Optional[int] = None
        self.evictions: list = []
        self.snapshot_evictions: list = []
        #: set when this session ran as a lane of a fused batch
        #: dispatch: {group, size, index}
        self.batch: Optional[dict] = None
        self.gate_wait_sec = 0.0
        self.t_submit = time.monotonic()
        self.t_admit: Optional[float] = None
        self.t_start: Optional[float] = None
        self.t_end: Optional[float] = None

    def describe(self) -> dict:
        return dict(
            session=self.id,
            kind=self.kind,
            lane=" ".join(self.argv),
            state=self.state,
            error=self.error,
            warm_start=self.warm_start,
            admitted_bytes=self.admitted_bytes,
            queue_wait_sec=round(self.gate_wait_sec, 6),
            unique=self.unique,
            total=self.total,
            duration_sec=(
                round(self.t_end - self.t_start, 6)
                if self.t_end is not None and self.t_start is not None
                else None
            ),
        )


class CheckService:
    """The resident service: one warm process, many sessions (module
    docstring). Thread-safe: ``check`` may be called concurrently from
    any number of threads (the HTTP server's per-request threads, a
    test's worker pool) — the FIFO gate arbitrates the device, the
    admission lock arbitrates the byte budget, and per-session tracers
    keep telemetry disjoint.

    ``program_budget_bytes`` bounds the compiled-program LRU (None =
    unbounded, the cold-process behavior); ``device_budget_bytes``
    bounds admitted sessions' projected resident bytes (None = admit
    everything); ``warm_start=False`` disables retention/resume (every
    session explores from wave 0). ``max_retained_sessions`` bounds
    the SETTLED-session registry — a resident daemon must not grow
    per query, so once the bound is crossed the oldest settled check
    sessions (their tracer events, captured output, and checker) are
    dropped from the registry; they disappear from ``status_block``
    and later ``write_trace`` exports (export before they rotate out
    if you need them), while live and Explorer sessions are always
    kept."""

    def __init__(self, *, program_budget_bytes: Optional[int] = None,
                 device_budget_bytes: Optional[int] = None,
                 spool_dir: Optional[str] = None,
                 warm_start: bool = True,
                 max_retained_sessions: int = 256,
                 batch_sessions: Optional[int] = None,
                 batch_window_sec: float = 0.25,
                 batch_waves_per_sync: Optional[int] = None,
                 snapshot_budget_bytes: Optional[int] = None):
        self.program_budget_bytes = program_budget_bytes
        self.device_budget_bytes = device_budget_bytes
        self.warm_start = warm_start
        self.max_retained_sessions = max_retained_sessions
        #: wave batching (stateright_tpu/batch.py): fuse up to N
        #: concurrent compatible check sessions into one device
        #: dispatch (None = off, every session runs solo FIFO).
        #: Sessions rendezvous for up to ``batch_window_sec`` — a
        #: group that fills earlier dispatches immediately, one that
        #: stays singleton falls back to the solo path with a
        #: one-line reason.
        self.batch_sessions = batch_sessions
        self.batch_window_sec = batch_window_sec
        self.batch_waves_per_sync = batch_waves_per_sync
        #: retained-snapshot spool byte budget (None = unbounded):
        #: the warm-start snapshots are priced by their on-disk
        #: manifest bytes and evicted LRU past the budget — the
        #: snapshot analogue of ``program_budget_bytes``.
        self.snapshot_budget_bytes = snapshot_budget_bytes
        self.spool_dir = spool_dir or tempfile.mkdtemp(
            prefix="stpu_serve_"
        )
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        self._gate = FifoLock()
        self._sessions: list[Session] = []
        self._ids = itertools.count()
        #: encoding fingerprint -> {path, bytes}: the byte-priced
        #: retained warm-start snapshot spool (most-recently-used
        #: last, same policy as the program LRU)
        self._warm: "OrderedDict[str, dict]" = OrderedDict()
        #: program-key-hash -> {key, bytes}: the byte-priced LRU view
        #: over the engines' _CHUNK_CACHE (most-recently-used last)
        self._lru: "OrderedDict[str, dict]" = OrderedDict()
        #: compatibility class key -> the currently-OPEN BatchGroup
        self._groups: dict = {}
        self._group_ids = itertools.count(1)
        #: settled fused groups (serve_summary's batches block rides
        #: the per-session ``batch`` trace events; this is the
        #: service-side admission record)
        self._batches: list[dict] = []
        #: encoding fingerprints ever admitted — the pre-warm
        #: registry: a repeat fingerprint kicks its program
        #: build-or-fetch on a worker thread at admission
        self._fp_registry: set = set()
        self._explorer = None  # (checker, snapshot, session)
        #: the live metrics plane (stateright_tpu/metrics.py): engine
        #: signals arrive through the tracer->metrics bridge at each
        #: session settle (zero engine code metered), the serve-only
        #: seams the tracer never sees — admission, FIFO queue
        #: depth/wait, gate hold, warm/cold split, evictions — are
        #: instrumented directly below. ``GET /.metrics`` renders
        #: this registry in Prometheus text format.
        self.metrics = MetricsRegistry()
        m = self.metrics
        self._m = dict(
            requests=m.counter(
                "stpu_serve_requests_total",
                "check sessions submitted to the service",
            ),
            active=m.gauge(
                "stpu_serve_active_sessions",
                "check sessions currently in flight",
            ),
            queue_depth=m.gauge(
                "stpu_serve_queue_depth",
                "sessions waiting on the FIFO device gate",
            ),
            queue_wait=m.histogram(
                "stpu_serve_queue_wait_seconds",
                "per-acquire FIFO device-gate wait",
            ),
            gate_hold=m.histogram(
                "stpu_serve_gate_hold_seconds",
                "device-gate hold per chunk dispatch+sync",
            ),
            sessions=m.counter(
                "stpu_serve_sessions_total",
                "settled sessions by final state",
            ),
            admission=m.counter(
                "stpu_serve_admission_total",
                "admission decisions (accepted/refused)",
            ),
            admission_bytes=m.counter(
                "stpu_serve_admission_bytes_total",
                "priced resident bytes by admission decision",
            ),
            warm=m.counter(
                "stpu_serve_warm_hits_total",
                "device sessions by warm/cold start",
            ),
            batch_fallbacks=m.counter(
                "stpu_serve_batch_fallbacks_total",
                "fused groups refused admission (fell back solo)",
            ),
            prog_evict=m.counter(
                "stpu_serve_program_evictions_total",
                "compiled-program LRU evictions",
            ),
            prog_evict_bytes=m.counter(
                "stpu_serve_program_evicted_bytes_total",
                "compiled-program bytes evicted",
            ),
            snap_evict=m.counter(
                "stpu_serve_snapshot_evictions_total",
                "warm-start snapshot spool evictions",
            ),
            snap_evict_bytes=m.counter(
                "stpu_serve_snapshot_evicted_bytes_total",
                "warm-start snapshot bytes evicted",
            ),
        )
        # pre-touch the unlabeled gauges so a fresh /.metrics scrape
        # shows the families at zero instead of omitting them
        self._m["active"].set(0)
        self._m["queue_depth"].set(0)

    # -- check sessions ---------------------------------------------------

    def check(self, argv) -> Session:
        """Run one CLI check lane as a session in the CALLING thread
        (callers provide their own concurrency — the HTTP server's
        request threads, a test's workers). Returns the settled
        Session; the lane's stdout (the reference-format report) is in
        ``session.output``, bit-identical in counts to a cold-process
        run of the same argv. Raises ValueError on runtime flags in
        the argv (see ``_FLAG_REFUSAL``); admission refusals and run
        errors land on the session, not as raises."""
        argv = [str(a) for a in argv]
        if any(a.startswith("--") for a in argv):
            raise ValueError(_FLAG_REFUSAL)
        from . import cli

        # only MODEL lanes are sessions: in particular `serve` must
        # never recurse into a nested daemon (a remote POST /.check
        # {"argv": ["serve", ...]} would block this thread in a
        # second serve_forever, forever)
        if not argv or argv[0] not in cli._MODELS:
            raise ValueError(
                f"unknown session lane {argv[:1] or '(empty)'}: "
                "service sessions run model check lanes only "
                f"({' | '.join(sorted(cli._MODELS))})"
            )

        session = Session(next(self._ids), "check", argv)
        session.tracer = telemetry.RunTracer()
        with self._lock:
            self._sessions.append(session)
        self._m["requests"].inc()
        self._m["active"].inc()
        proxy = _stdout_proxy()
        buf = io.StringIO()
        proxy.push(buf)
        session.t_start = time.monotonic()
        try:
            with session.tracer.activate_thread():
                cli._SESSION_HOOK.hook = self._session_hook(session)
                try:
                    cli.main(argv)
                    session.state = "done"
                except AdmissionRefused as exc:
                    session.state = "refused"
                    session.error = str(exc)
                    print(f"REFUSED: {exc}")
                except SystemExit as exc:
                    code = exc.code
                    if code in (None, 0):
                        session.state = "done"
                    else:
                        session.state = "failed"
                        session.error = str(code)
                except Exception as exc:
                    session.state = "failed"
                    session.error = f"{type(exc).__name__}: {exc}"
                finally:
                    cli._SESSION_HOOK.hook = None
                # retention + attribution while the session tracer is
                # still the thread's tracer, so the checkpoint event
                # of the retained snapshot lands in THIS trace
                self._finish(session)
        finally:
            proxy.pop()
            session.output = buf.getvalue()
            session.t_end = time.monotonic()
            session.running = False
            self._m["active"].dec()
            self._m["sessions"].inc(state=session.state)
            # the tracer->metrics bridge: every schema-validated event
            # this session emitted (chunk walls, build tiers, verdict
            # timeline, spills, checkpoints, ...) feeds the live
            # registry — zero engine code metered, each session's
            # stream folded exactly once, at settle
            with session.tracer._lock:
                settled = list(session.tracer.events)
            bridge_events(settled, self.metrics)
            self._trim_sessions()
        return session

    def _trim_sessions(self) -> None:
        """Bound the settled-session registry (the resident process
        must not grow per query): drop the oldest settled check
        sessions past ``max_retained_sessions``. Live (running /
        queued) and Explorer sessions always stay."""
        cap = self.max_retained_sessions
        if cap is None:
            return
        with self._lock:
            settled = [s for s in self._sessions
                       if s.kind == "check" and not s.running
                       and s.t_end is not None]
            excess = len(settled) - cap
            if excess <= 0:
                return
            drop = set(id(s) for s in settled[:excess])
            self._sessions = [s for s in self._sessions
                              if id(s) not in drop]

    def _session_hook(self, session: Session):
        """The callback cli._report runs on the freshly-spawned
        checker, before its first join: admission, warm-start staging,
        the FIFO gate, and final-carry retention arming — everything
        the service needs, at the one seam every check lane shares."""

        def hook(checker) -> None:
            session.checker = checker
            if not hasattr(checker, "_run_attempt"):
                # host engines: no device work to admit or gate; the
                # session still traces and reports
                session.t_admit = time.monotonic()
                session.running = True
                return
            session.device = True
            fp = checkpoint.encoding_fingerprint(checker)
            session.encoding_fp = fp
            warm_entry = (self._warm.get(fp)
                          if self.warm_start else None)
            # wave batching: a warm-startable session settles in one
            # chunk solo — resuming beats fusing, so only sessions
            # with no retained snapshot rendezvous
            if self.batch_sessions and warm_entry is None:
                from .batch import batch_eligible

                key, _reason = batch_eligible(checker)
                if key is not None and self._join_batch(
                    key, session, checker
                ):
                    return
            self._solo_setup(session, checker)

        return hook

    def _solo_setup(self, session: Session, checker) -> None:
        """The round-18 solo session path: admission, pre-warm,
        warm-start staging, retention arming, and the FIFO gate. Also
        the landing spot when a batch seat falls back (the member's
        ``solo_prepare``)."""
        self._admit(session, checker)
        fp = session.encoding_fp
        if fp is not None:
            with self._lock:
                seen = fp in self._fp_registry
                self._fp_registry.add(fp)
            if seen and not getattr(checker, "_prewarm_wait", None):
                # ROADMAP 3(d): cold time-to-first-wave is
                # compile-dominated — a repeat fingerprint's program
                # build-or-fetch starts NOW, off-thread, instead of
                # inside the session's first dispatch
                self._prewarm(session, checker)
        if self.warm_start and fp is not None:
            entry = self._warm.get(fp)
            if entry is not None:
                try:
                    checker.resume_from(entry["path"])
                    session.warm_start = True
                    with self._lock:
                        self._warm.move_to_end(fp)
                except checkpoint.SnapshotError:
                    # stale/incompatible retention: run cold —
                    # correctness never rides the cache
                    session.warm_start = False
        checker.keep_final_carry = True
        checker.dispatch_gate = _GateHandle(
            self._gate, session, self._m
        )

    # -- admission-time program pre-warm ----------------------------------

    def _prewarm(self, session: Session, checker) -> None:
        """Kick the program build-or-fetch on a worker thread and
        install the ``_prewarm_wait`` seam (checkers/tpu.py
        ``_lookup_programs`` joins it before its own lookup, so the
        worker's cache insert and the run's lookup cannot race). The
        joined result is ledger-attributed as a ``program_build``
        event with a ``prewarm`` marker under the session tracer."""
        from .checkers import tpu as _tpu

        res: dict = {}

        def worker():
            snap = _tpu._monitor_snapshot()
            t0 = time.monotonic()
            try:
                iv = checker.encoded.init_vecs()
                n0 = len({
                    checker._vec_fp(iv[i]) for i in range(len(iv))
                })
                seed_fn, chunk_fn = checker._lookup_programs(n0)
                import jax
                import jax.numpy as jnp

                spec = jax.eval_shape(
                    seed_fn,
                    jax.ShapeDtypeStruct(
                        (n0, checker.encoded.width), jnp.uint32
                    ),
                )
                # AOT backend compile-or-fetch: the run's own jit call
                # re-traces, but its backend half dedups against the
                # persistent XLA cache this compile just populated
                chunk_fn.lower(spec).compile()
                tier, wall, cold = _tpu._resolve_tier(
                    _tpu._monitor_delta(snap)
                )
                res.update(
                    tier=tier,
                    wall=wall or (time.monotonic() - t0),
                    cold=cold,
                )
            except Exception as exc:
                res["error"] = f"{type(exc).__name__}: {exc}"

        th = threading.Thread(
            target=worker, name=f"prewarm-{session.id}", daemon=True
        )
        emitted = [False]

        def wait():
            if threading.current_thread() is th:
                return  # the worker's own lookup must not self-join
            th.join()
            if emitted[0] or "tier" not in res:
                return
            emitted[0] = True
            tracer = telemetry.current_tracer()
            if tracer is not None:
                tracer.event(
                    "program_build", program="programs",
                    tier=res["tier"],
                    key=getattr(checker, "_program_key_hash", None),
                    wall_sec=round(res["wall"], 6),
                    cold_sec=(None if res.get("cold") is None
                              else round(res["cold"], 6)),
                    prewarm=True,
                )

        checker._prewarm_wait = wait
        th.start()

    # -- wave batching -----------------------------------------------------

    def _join_batch(self, key, session: Session, checker) -> bool:
        """Claim a seat in the open batch group of this compatibility
        class (opening a fresh group when none is open or the open one
        froze), and swap the checker's ``_run`` for the group's
        member entry point. Returns False when a seat could not be
        claimed (the session runs solo)."""
        from .batch import BatchGroup

        with self._lock:
            group = self._groups.get(key)
            member = (group.try_join(checker, " ".join(session.argv))
                      if group is not None else None)
            if member is None:
                group = BatchGroup(
                    next(self._group_ids), key,
                    max_sessions=int(self.batch_sessions),
                    window_sec=self.batch_window_sec,
                    waves_per_sync=self.batch_waves_per_sync,
                )
                group.admit = (
                    lambda fused, members, g=group:
                    self._admit_fused(g, fused, members)
                )
                group.make_gate = (
                    lambda g=group: _FusedGateHandle(
                        self._gate,
                        [m.session for m in g.members],
                        self._m,
                    )
                )
                self._groups[key] = group
                member = group.try_join(
                    checker, " ".join(session.argv)
                )
            if member is None:
                return False
        member.session = session
        member.notify = print  # session thread: the stdout proxy

        def solo_prepare():
            session.batch = None  # this session did not batch
            self._solo_setup(session, checker)

        member.solo_prepare = solo_prepare
        session.batch = dict(
            group=group.group_id, size=None, index=member.index
        )
        checker._run = (
            lambda reporter=None: group.member_run(member, reporter)
        )
        return True

    def _admit_fused(self, group, fused, members) -> Optional[str]:
        """Admission for a FUSED plan (the batch analogue of
        :meth:`_admit`, invoked by the group leader at freeze):
        price the fused engine's resident plan via the memplan ledger
        against the device budget minus other in-flight sessions.
        Returns None (admitted — every member session is marked
        running with its amortized byte share) or a one-line refusal
        reason (the group falls back to solo FIFO, where each session
        faces the ordinary solo admission)."""
        plan = memplan.fused_session_bytes(fused, len(members))
        sessions = [m.session for m in members]
        with self._lock:
            in_flight = sum(
                s.admitted_bytes or 0
                for s in self._sessions
                if s.running and s.device and s not in sessions
            )
            budget = self.device_budget_bytes
            if (budget is not None
                    and plan["total_bytes"] + in_flight > budget):
                self._m["batch_fallbacks"].inc()
                return (
                    f"batch: fused plan of {len(members)} session(s) "
                    f"projects {plan['total_bytes']:,} resident "
                    f"bytes ({in_flight:,} already in flight, device "
                    f"budget {budget:,}); falling back to solo FIFO"
                )
            now = time.monotonic()
            for s in sessions:
                s.admitted_bytes = plan["per_session_bytes"]
                s.t_admit = now
                s.running = True
                if s.batch is not None:
                    s.batch["size"] = len(members)
                self._m["admission"].inc(decision="accepted")
                self._m["admission_bytes"].inc(
                    plan["per_session_bytes"], decision="accepted"
                )
            self._batches.append(dict(
                group=group.group_id,
                size=len(members),
                sessions=[s.id for s in sessions],
                class_key=str(group.class_key),
                plan_bytes=plan["total_bytes"],
                per_session_bytes=plan["per_session_bytes"],
            ))
            # this group is dispatching: close the class slot so the
            # next arrival opens a fresh group
            if self._groups.get(group.class_key) is group:
                del self._groups[group.class_key]
        return None

    def _admit(self, session: Session, checker) -> None:
        """The admission check (ISSUE contract: against the capacity
        pricing, BEFORE device work): projected resident bytes from
        config alone vs the device budget minus in-flight sessions'
        admissions. Refuses loudly; never queues an oversized query
        into a mid-run OOM."""
        est = memplan.session_resident_bytes(checker)
        with self._lock:
            in_flight = sum(
                s.admitted_bytes or 0
                for s in self._sessions
                if s.running and s.device and s is not session
            )
            budget = self.device_budget_bytes
            if (budget is not None
                    and est["total_bytes"] + in_flight > budget):
                session.error = (
                    f"admission refused: session projects "
                    f"{est['total_bytes']:,} resident bytes "
                    f"(visited {est['visited_bytes']:,} + frontier "
                    f"{est['frontier_bytes']:,} + candidates "
                    f"{est['cand_bytes']:,}), {in_flight:,} already "
                    f"in flight, device budget "
                    f"{budget:,} — shrink the lane's capacity or "
                    "raise the service budget"
                )
                self._m["admission"].inc(decision="refused")
                self._m["admission_bytes"].inc(
                    est["total_bytes"], decision="refused"
                )
                raise AdmissionRefused(session.error)
            session.admitted_bytes = est["total_bytes"]
            session.t_admit = time.monotonic()
            session.running = True
            self._m["admission"].inc(decision="accepted")
            self._m["admission_bytes"].inc(
                est["total_bytes"], decision="accepted"
            )

    def _finish(self, session: Session) -> None:
        checker = session.checker
        if checker is None:
            return
        session.unique = getattr(checker, "_unique_states", None)
        session.total = getattr(checker, "_total_states", None)
        if not session.device or session.state != "done":
            return
        self._m["warm"].inc(
            result="warm" if session.warm_start else "cold"
        )
        session.program_key = getattr(
            checker, "_program_key_hash", None
        )
        plan = getattr(checker, "memory_plan", None)
        if plan is not None:
            session.plan_bytes = int(plan["total_bytes"])
        if self.warm_start and session.encoding_fp:
            key = hashlib.sha1(
                session.encoding_fp.encode()
            ).hexdigest()[:16]
            path = os.path.join(self.spool_dir, f"warm_{key}.ckpt")
            try:
                manifest = checkpoint.retain_final_snapshot(
                    checker, path
                )
                if manifest is not None:
                    with self._lock:
                        self._warm[session.encoding_fp] = dict(
                            key=key, path=path,
                            bytes=int(
                                manifest.get("snapshot_bytes") or 0
                            ),
                        )
                        self._warm.move_to_end(session.encoding_fp)
                    self._spool_evict(session)
            except Exception:
                pass  # retention is an optimization, never a failure
        # the retained snapshot (or nothing) is the warm state now —
        # drop the device-resident final carry so completed sessions
        # don't pin HBM
        checker._final_carry = None
        self._lru_note(session, checker)

    # -- retained-snapshot spool LRU --------------------------------------

    def _spool_evict(self, session: Session) -> None:
        """Bound the warm-start snapshot spool by BYTES, the same LRU
        policy the compiled-program cache uses: evict the
        least-recently-used retained snapshots past
        ``snapshot_budget_bytes`` (never the one just retained). An
        evicted fingerprint's next re-check runs cold — counts
        unaffected, only the warm start is lost."""
        budget = self.snapshot_budget_bytes
        if budget is None:
            return
        evicted = []
        with self._lock:
            total = sum(e["bytes"] for e in self._warm.values())
            while total > budget and len(self._warm) > 1:
                fp, entry = next(iter(self._warm.items()))
                if fp == session.encoding_fp:
                    break
                self._warm.pop(fp)
                total -= entry["bytes"]
                evicted.append(entry)
                session.snapshot_evictions.append(
                    (entry["key"], entry["bytes"])
                )
                self._m["snap_evict"].inc()
                self._m["snap_evict_bytes"].inc(entry["bytes"])
        for entry in evicted:
            try:
                os.remove(entry["path"])
            except OSError:
                pass

    def spool_bytes(self) -> int:
        with self._lock:
            return sum(e["bytes"] for e in self._warm.values())

    # -- compiled-program LRU ---------------------------------------------

    def _lru_note(self, session: Session, checker) -> None:
        """Record this session's program use in the byte-priced LRU
        and evict past the budget. Attribution is EXACT: the checker's
        ``_program_key_hash`` identifies its ``_CHUNK_CACHE`` entry
        (the same key the XLA persistent cache derives from), and the
        entry is priced by the session's memplan ledger total. The
        entry the session just used is never evicted — the budget
        bounds the TAIL, not the working program."""
        from .checkers import tpu as _tpu

        key_hash = session.program_key
        if key_hash is None:
            return
        with self._lock:
            entry = self._lru.get(key_hash)
            if entry is None:
                for key in list(_tpu._CHUNK_CACHE):
                    if _tpu._key_hash(key) == key_hash:
                        self._lru[key_hash] = dict(
                            key=key,
                            bytes=int(session.plan_bytes or 0),
                        )
                        break
            else:
                self._lru.move_to_end(key_hash)
                if session.plan_bytes:
                    entry["bytes"] = int(session.plan_bytes)
            budget = self.program_budget_bytes
            if budget is None:
                return
            total = sum(e["bytes"] for e in self._lru.values())
            while total > budget and len(self._lru) > 1:
                old_hash = next(iter(self._lru))
                if old_hash == key_hash:
                    break
                entry = self._lru.pop(old_hash)
                _tpu._CHUNK_CACHE.pop(entry["key"], None)
                total -= entry["bytes"]
                session.evictions.append(
                    (old_hash, entry["bytes"])
                )
                self._m["prog_evict"].inc()
                self._m["prog_evict_bytes"].inc(entry["bytes"])

    def lru_bytes(self) -> int:
        with self._lock:
            return sum(e["bytes"] for e in self._lru.values())

    # -- Explorer mount ---------------------------------------------------

    def mount_explorer(self, builder, name: Optional[str] = None):
        """Attach one Explorer model to the service: spawns the
        on-demand checker, opens a long-lived ``explorer`` session
        whose tracer meters every HTTP request (the round-14
        ``explorer_request`` spans, installed around each request via
        :meth:`request_scope`). Returns ``(checker, snapshot)`` for
        :func:`explorer.server.make_server`."""
        from .explorer.server import Snapshot

        checker = builder.spawn_on_demand()
        snapshot = Snapshot()
        model = name or type(checker.model).__name__
        session = Session(next(self._ids), "explorer", ("explore", model))
        session.tracer = telemetry.RunTracer()
        session.tracer.begin_run(
            lane=dict(engine="explorer", model=model)
        )
        session.state = "serving"
        session.t_admit = session.t_start = time.monotonic()
        with self._lock:
            self._sessions.append(session)
            self._explorer = (checker, snapshot, session)
        return checker, snapshot

    def http_server(self, host: str, port: int):
        """The service's HTTP server: the Explorer server (when one is
        mounted) with the service's routes and session registry on top
        — one server, both tenancies (explorer/server.py
        ``make_server(registry=...)``)."""
        from .explorer.server import Snapshot, make_server

        if self._explorer is not None:
            checker, snapshot, _ = self._explorer
        else:
            checker, snapshot = None, Snapshot()
        return make_server(checker, snapshot, host, port,
                           registry=self)

    # -- the make_server registry protocol --------------------------------

    def handle_request(self, handler, method: str, path: str) -> bool:
        """Service routes, tried before the Explorer's: ``POST
        /.check`` runs a session from JSON ``{"argv": [...]}`` (the
        ``--connect`` client's endpoint), ``GET /.serve/sessions``
        lists sessions, ``POST /.serve/trace`` exports the merged
        TRACE artifact pair, ``GET /.metrics`` renders the live
        registry in Prometheus text format (beside ``/.status``, same
        snapshot discipline: the registry lock is only ever held for
        dict reads, never across device work, so a scrape answers
        while a session is mid-chunk). Returns True when handled."""
        if method == "GET" and path == "/.metrics":
            body = self.metrics.render_prometheus().encode()
            handler.send_response(200)
            handler.send_header(
                "Content-Type",
                "text/plain; version=0.0.4; charset=utf-8",
            )
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
            return True
        if method == "POST" and path == "/.check":
            try:
                length = int(handler.headers.get("Content-Length") or 0)
                body = json.loads(
                    handler.rfile.read(length) or b"{}"
                )
                argv = [str(a) for a in (body.get("argv") or [])]
                session = self.check(argv)
            except (ValueError, TypeError) as exc:
                handler._json(dict(ok=False, error=str(exc)), code=400)
                return True
            handler._json(dict(
                ok=session.state == "done",
                session=session.describe(),
                output=session.output,
            ))
            return True
        if method == "GET" and path == "/.serve/sessions":
            handler._json(self.status_block())
            return True
        if method == "POST" and path == "/.serve/trace":
            jsonl, chrome = self.write_trace()
            handler._json(dict(jsonl=jsonl, chrome=chrome))
            return True
        return False

    def request_scope(self):
        """Context manager installed around each Explorer request: the
        explorer session's tracer becomes the request thread's tracer,
        so the per-request spans land in that session's stream."""
        ex = self._explorer
        if ex is None:
            return nullcontext()
        return ex[2].tracer.activate_thread()

    def status_block(self) -> dict:
        """Lock-free-readable service snapshot for ``/.status`` /
        ``/.serve/sessions`` (GIL-atomic attribute reads, the Explorer
        status view's progress-poll contract)."""
        with self._lock:
            sessions = [s.describe() for s in self._sessions]
            lru_bytes = sum(e["bytes"] for e in self._lru.values())
            lru_len = len(self._lru)
            warm_n = len(self._warm)
            spool = sum(e["bytes"] for e in self._warm.values())
            n_batches = len(self._batches)
        return dict(
            sessions=sessions,
            programs=dict(
                cached=lru_len,
                bytes=lru_bytes,
                budget_bytes=self.program_budget_bytes,
            ),
            device_budget_bytes=self.device_budget_bytes,
            warm_models=warm_n,
            snapshots=dict(
                retained=warm_n,
                bytes=spool,
                budget_bytes=self.snapshot_budget_bytes,
            ),
            batching=dict(
                batch_sessions=self.batch_sessions,
                window_sec=self.batch_window_sec,
                groups_dispatched=n_batches,
            ),
            # the compact live-metrics block (ISSUE 19): progress
            # polls answer the load question without scraping
            # /.metrics — registry reads only, never device waits
            metrics=dict(
                active_sessions=int(self._m["active"].value()),
                queue_depth=int(self._m["queue_depth"].value()),
                refusals=int(
                    self._m["admission"].value(decision="refused")
                ),
                ttv_p99_sec=self.metrics.histogram_quantile(
                    "stpu_time_to_verdict_seconds", 0.99
                ),
            ),
        )

    # -- merged trace export ----------------------------------------------

    def events(self) -> list:
        """Merge every session's tracer events into ONE stream:
        sessions get disjoint run indices (submission order), times
        rebase to the service clock, and each session is bracketed by
        ``session_begin``/``session_end`` service events (plus
        ``program_evict`` rows for evictions it triggered). The result
        validates under telemetry.validate_events and diffs/derives
        like any TRACE."""
        with self._lock:
            sessions = list(self._sessions)
        out: list[dict] = []
        base = 0
        now = time.monotonic()
        for s in sessions:
            tracer = s.tracer
            evs: list[dict] = []
            if tracer is not None:
                # NB: a live Explorer session's run stays OPEN — an
                # export is a read, not a shutdown, and must be
                # idempotent (the session keeps serving and later
                # exports see the later requests); a run without a
                # run_end is valid to every consumer (validate_events,
                # _run_view, serve_summary)
                with tracer._lock:
                    evs = [dict(e) for e in tracer.events]
            runs = sorted({
                e["run"] for e in evs
                if isinstance(e.get("run"), int) and e["run"] >= 0
            }) or [0]
            run_map = {r: base + i for i, r in enumerate(runs)}
            rb = base
            offset = ((tracer._t_base - self._t0)
                      if tracer is not None else 0.0)
            t_admit = s.t_admit if s.t_admit is not None else s.t_submit
            out.append(dict(
                ev="session_begin", run=rb, session=s.id,
                kind=s.kind, t=round(t_admit - self._t0, 6),
                lane=" ".join(s.argv),
                admitted_bytes=s.admitted_bytes,
                admission_wait_sec=round(t_admit - s.t_submit, 6),
                warm_start=s.warm_start,
            ))
            for e in evs:
                r = e.get("run")
                if isinstance(r, int):
                    e["run"] = run_map.get(r, rb)
                for k in ("t", "t0", "t1"):
                    v = e.get(k)
                    if isinstance(v, (int, float)):
                        e[k] = round(v + offset, 6)
                out.append(e)
            t_end = s.t_end if s.t_end is not None else now
            out.append(dict(
                ev="session_end", run=rb, session=s.id,
                state=s.state, t=round(t_end - self._t0, 6),
                error=s.error, unique=s.unique, total=s.total,
                queue_wait_sec=round(s.gate_wait_sec, 6),
                warm_start=s.warm_start,
                program_key=s.program_key,
                duration_sec=(
                    round(t_end - s.t_start, 6)
                    if s.t_start is not None else None
                ),
            ))
            for key_hash, nbytes in s.evictions:
                out.append(dict(
                    ev="program_evict", run=rb, key=key_hash,
                    bytes=int(nbytes), t=round(t_end - self._t0, 6),
                ))
            for key_hash, nbytes in s.snapshot_evictions:
                out.append(dict(
                    ev="snapshot_evict", run=rb, key=key_hash,
                    bytes=int(nbytes), t=round(t_end - self._t0, 6),
                ))
            base += len(runs)
        return out

    def write_trace(self, root: Optional[str] = None,
                    round: Optional[int] = None) -> tuple[str, str]:
        """Export the merged stream as an auto-numbered TRACE artifact
        pair (JSONL + Chrome trace) — the input tools/serve_report.py
        derives ``SERVE_r*`` from."""
        tracer = telemetry.RunTracer()
        tracer.events = self.events()
        return telemetry.write_artifacts(tracer, root=root,
                                         round=round)


# -- the derived per-session summary (tools/serve_report.py) --------------


def serve_summary(events: list) -> Optional[dict]:
    """Derive the per-session latency-per-query view from a service
    trace's ``session_begin``/``session_end`` events and each
    session's run events: time-to-verdict, queue wait, compile-tier
    ledger, cache hits, and the warm-vs-cold pairing (repeat queries
    of one program key vs their cold first query, with the
    time-to-verdict delta attributed between the compile tier and
    dispatch). None when the trace carries no session events (not a
    service trace) — serve_report exits 2 on that."""
    from .telemetry import _run_view

    begins = [e for e in events if e.get("ev") == "session_begin"]
    if not begins:
        return None
    ends = {e["session"]: e for e in events
            if e.get("ev") == "session_end"}
    batch_by_run = {e["run"]: e for e in events
                    if e.get("ev") == "batch"}
    sessions = []
    for sb in sorted(begins, key=lambda e: e["session"]):
        run = sb["run"]
        view = _run_view(events, run)
        se = ends.get(sb["session"], {})
        tiers: dict[str, int] = {}
        for b in view["builds"]:
            tiers[b["tier"]] = tiers.get(b["tier"], 0) + 1
        build_wall = sum(
            b.get("wall_sec") or 0.0 for b in view["builds"]
        )
        cold = sum(b.get("cold_sec") or 0.0 for b in view["builds"])
        t0_run = (view["begin"] or {}).get("t", sb["t"])
        verdicts = [
            dict(
                {k: v for k, v in ev.items()
                 if k not in ("ev", "run", "t")},
                t_since_run=round(ev["t"] - t0_run, 6),
            )
            for ev in view["verdicts"]
        ]
        ttv = max(
            (v["t_since_run"] for v in verdicts), default=None
        )
        prof = view["latency_profile"] or {}
        spans = [s for s in view["spans"]
                 if s.get("phase") == "explorer_request"]
        sessions.append(dict(
            session=sb["session"],
            run=run,
            kind=sb["kind"],
            lane=sb.get("lane"),
            state=se.get("state"),
            error=se.get("error"),
            warm_start=bool(se.get("warm_start",
                                   sb.get("warm_start"))),
            admitted_bytes=sb.get("admitted_bytes"),
            admission_wait_sec=sb.get("admission_wait_sec"),
            queue_wait_sec=se.get("queue_wait_sec"),
            unique=se.get("unique"),
            total=se.get("total"),
            duration_sec=se.get("duration_sec"),
            chunks=prof.get("chunks"),
            waves=prof.get("waves"),
            resumed_from_wave=prof.get("resumed_from_wave"),
            time_to_first_wave_sec=prof.get(
                "time_to_first_wave_sec"
            ),
            dispatch_net_sec=prof.get("dispatch_net_sec"),
            fetch_sec=prof.get("fetch_sec"),
            time_to_verdict_sec=ttv,
            verdicts=verdicts,
            builds=dict(
                tiers=tiers,
                wall_sec=round(build_wall, 6),
                cold_sec=round(cold, 6),
            ),
            program_key=se.get("program_key"),
            batch=(
                {k: batch_by_run[run][k]
                 for k in ("group", "size", "index", "chunks")
                 if k in batch_by_run[run]}
                if run in batch_by_run else None
            ),
            explorer=(dict(
                requests=len(spans),
                cache_hits=sum(
                    1 for s in spans if s.get("cache_hit")
                ),
            ) if spans else None),
        ))
    evictions = [
        {k: v for k, v in e.items() if k != "ev"}
        for e in events if e.get("ev") == "program_evict"
    ]
    snapshot_evictions = [
        {k: v for k, v in e.items() if k != "ev"}
        for e in events if e.get("ev") == "snapshot_evict"
    ]
    return dict(
        sessions=sessions,
        evictions=evictions,
        snapshot_evictions=snapshot_evictions,
        warm_vs_cold=_warm_vs_cold(sessions),
        batches=_batch_groups(sessions),
    )


def _batch_groups(sessions: list) -> list:
    """Aggregate the per-session ``batch`` lanes into per-group rows:
    occupancy (which sessions shared the fused dispatch, how many
    fused chunks they rode) and the amortized floor per query — each
    member's dispatch+sync overhead is already its 1/N_active share
    of the fused walls, so the mean per-query overhead IS the
    amortized sync floor serve_report tables against the solo
    baseline."""
    groups: dict = {}
    for s in sessions:
        b = s.get("batch")
        if not b:
            continue
        g = groups.setdefault(b["group"], dict(
            group=b["group"],
            size=b.get("size"),
            sessions=[],
            chunks=b.get("chunks"),
            members=[],
        ))
        g["sessions"].append(s["session"])
        overhead = ((s.get("dispatch_net_sec") or 0.0)
                    + (s.get("fetch_sec") or 0.0))
        g["members"].append(dict(
            session=s["session"],
            waves=s.get("waves"),
            dispatch_net_sec=s.get("dispatch_net_sec"),
            fetch_sec=s.get("fetch_sec"),
            overhead_sec=round(overhead, 6),
            time_to_verdict_sec=s.get("time_to_verdict_sec"),
        ))
    out = []
    for g in sorted(groups.values(), key=lambda g: g["group"]):
        ov = [m["overhead_sec"] for m in g["members"]]
        g["per_query_overhead_sec"] = (
            round(sum(ov) / len(ov), 6) if ov else None
        )
        out.append(g)
    return out


def _warm_vs_cold(sessions: list) -> list:
    """Pair repeat check queries with their cold first query (same
    program key): per pair, the time-to-verdict delta and where the
    ledger says it went — the compile tier (build walls) vs dispatch
    proper (``dispatch_net_sec``, compile already subtracted). The
    acceptance read: a healthy warm query's ttv sits below the cold
    one with the difference on the compile side."""
    by_key: dict[str, list] = {}
    for s in sessions:
        if s["kind"] == "check" and s.get("program_key"):
            by_key.setdefault(s["program_key"], []).append(s)
    out = []
    for key, group in sorted(by_key.items()):
        if len(group) < 2:
            continue
        cold = group[0]
        for warm in group[1:]:
            c_ttv, w_ttv = (cold.get("time_to_verdict_sec"),
                            warm.get("time_to_verdict_sec"))
            out.append(dict(
                program_key=key,
                cold_session=cold["session"],
                warm_session=warm["session"],
                warm_start=warm.get("warm_start"),
                cold_ttv_sec=c_ttv,
                warm_ttv_sec=w_ttv,
                ttv_delta_sec=(
                    round(c_ttv - w_ttv, 6)
                    if c_ttv is not None and w_ttv is not None
                    else None
                ),
                compile_delta_sec=round(
                    (cold["builds"]["wall_sec"]
                     - warm["builds"]["wall_sec"]), 6
                ),
                dispatch_net_delta_sec=(
                    round((cold.get("dispatch_net_sec") or 0.0)
                          - (warm.get("dispatch_net_sec") or 0.0), 6)
                ),
                waves_cold=cold.get("waves"),
                waves_warm=warm.get("waves"),
            ))
    return out


def write_serve_artifact(summary: dict,
                         root: Optional[str] = None,
                         metrics: Optional[dict] = None) -> str:
    """Write one auto-numbered ``SERVE_r*.json`` (own round sequence,
    like MEM/LAT/COMM — derived from a TRACE it names in its ``trace``
    field; numbering via stateright_tpu/artifacts.py). ``metrics``
    embeds a registry families snapshot
    (:meth:`~stateright_tpu.metrics.MetricsRegistry.snapshot`) beside
    the summary — the live-plane view of the same run."""
    from .artifacts import artifact_path, next_round, provenance, \
        repo_root

    root = repo_root() if root is None else root
    path = artifact_path(
        "SERVE", "json", root=root,
        round=next_round(root, stems=("SERVE",)),
    )
    doc = dict(summary)
    if metrics is not None:
        doc["metrics"] = metrics
    doc.setdefault("provenance", provenance())
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path


# -- daemon + client (the CLI's `serve` / `--connect` lanes) --------------


def explorer_builder(name: str, count: Optional[int] = None):
    """A CheckerBuilder for the daemon's ``--explore=MODEL[,COUNT]``
    mount (the same model constructors the CLI lanes use)."""
    if name == "2pc":
        from .models.two_phase_commit import TwoPhaseSys

        return TwoPhaseSys(rm_count=count or 2).checker()
    if name == "paxos":
        from .models.paxos import PaxosModelCfg, paxos_model

        return paxos_model(
            PaxosModelCfg(client_count=count or 2, server_count=3)
        ).checker()
    if name == "increment":
        from .models.increment import Increment

        return Increment(thread_count=count or 2).checker()
    if name == "single-copy-register":
        from .models.single_copy_register import (
            SingleCopyRegisterCfg,
            single_copy_register_model,
        )

        return single_copy_register_model(
            SingleCopyRegisterCfg(client_count=count or 2)
        ).checker()
    if name == "linearizable-register":
        from .models.linearizable_register import (
            AbdModelCfg,
            abd_model,
        )

        return abd_model(AbdModelCfg(client_count=count or 2)).checker()
    raise SystemExit(
        f"serve --explore: unknown model {name!r} (2pc | paxos | "
        "increment | single-copy-register | linearizable-register)"
    )


def daemon_main(argv: list) -> int:
    """``python -m stateright_tpu serve [HOST:PORT] [--explore=MODEL
    [,COUNT]] [--program-budget-bytes=N] [--device-budget-bytes=N]
    [--batch-sessions[=N]] [--batch-window-sec=S]
    [--snapshot-budget-bytes=N] [--no-warm-start]
    [--metrics-interval=N [--metrics-path=FILE]]`` — run the
    resident service until interrupted. Clients reach it with
    ``--connect=HOST:PORT`` on any check lane, a browser at ``/``
    when an Explorer model is mounted. ``--batch-sessions`` fuses up
    to N (default 4) concurrent compatible check sessions into one
    device dispatch (stateright_tpu/batch.py). ``--metrics-interval``
    appends one ``metrics_rollup`` JSONL line (the live registry,
    cumulative) every N seconds — the headless export for mesh runs
    with no scraper; ``GET /.metrics`` serves the same registry in
    Prometheus text format either way."""
    addr = "localhost:3000"
    explore = None
    metrics_interval = None
    metrics_path = None
    kw: dict = {}
    for a in argv:
        if a.startswith("--explore="):
            spec = a.split("=", 1)[1]
            name, _, count = spec.partition(",")
            explore = (name, int(count) if count else None)
        elif a.startswith("--metrics-interval="):
            metrics_interval = float(a.split("=", 1)[1])
        elif a.startswith("--metrics-path="):
            metrics_path = a.split("=", 1)[1]
        elif a.startswith("--program-budget-bytes="):
            kw["program_budget_bytes"] = int(a.split("=", 1)[1])
        elif a.startswith("--device-budget-bytes="):
            kw["device_budget_bytes"] = int(a.split("=", 1)[1])
        elif a == "--batch-sessions":
            kw["batch_sessions"] = 4
        elif a.startswith("--batch-sessions="):
            kw["batch_sessions"] = int(a.split("=", 1)[1])
        elif a.startswith("--batch-window-sec="):
            kw["batch_window_sec"] = float(a.split("=", 1)[1])
        elif a.startswith("--snapshot-budget-bytes="):
            kw["snapshot_budget_bytes"] = int(a.split("=", 1)[1])
        elif a == "--no-warm-start":
            kw["warm_start"] = False
        elif a.startswith("--"):
            raise SystemExit(f"serve: unknown flag {a}")
        else:
            addr = a
    service = CheckService(**kw)
    if explore is not None:
        service.mount_explorer(
            explorer_builder(*explore), explore[0]
        )
    host, _, port = addr.partition(":")
    server = service.http_server(host or "localhost",
                                 int(port or 3000))
    rollup = None
    if metrics_interval is not None:
        if metrics_path is None:
            metrics_path = "stateright_tpu.metrics.jsonl"
        rollup = Rollup(
            metrics_path, metrics_interval,
            source=lambda: service.metrics,
        ).start()
    elif metrics_path is not None:
        raise SystemExit(
            "serve: --metrics-path requires --metrics-interval=N"
        )
    print(
        f"Resident checking service on http://{addr} "
        f"(POST /.check, GET /.serve/sessions, POST /.serve/trace, "
        f"GET /.metrics"
        + (", Explorer UI at /" if explore is not None else "")
        + "). Connect check lanes with --connect=" + addr
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if rollup is not None:
            rollup.stop()
    return 0


def client_main(addr: str, argv: list) -> int:
    """``--connect=HOST:PORT`` client mode: ship the lane argv to the
    resident service, print its captured report verbatim (counts
    bit-identical to a cold-process run of the same argv — it IS the
    same handler, warm). Returns the exit status."""
    import urllib.error
    import urllib.request

    if any(a.startswith("--") for a in argv):
        print(f"--connect: {_FLAG_REFUSAL}", file=sys.stderr)
        return 2
    body = json.dumps({"argv": argv}).encode()
    req = urllib.request.Request(
        f"http://{addr}/.check", data=body,
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req) as r:
            resp = json.loads(r.read())
    except (urllib.error.URLError, OSError) as exc:
        print(
            f"--connect: no resident service at {addr} ({exc}); "
            "start one with `python -m stateright_tpu serve "
            f"{addr}`",
            file=sys.stderr,
        )
        return 2
    sys.stdout.write(resp.get("output") or "")
    err = resp.get("error") or (resp.get("session") or {}).get("error")
    if not resp.get("ok") and err:
        print(f"session failed: {err}", file=sys.stderr)
    return 0 if resp.get("ok") else 1
