"""Deterministic fault injection: the harness that proves the
checkpoint/resume path (stateright_tpu/checkpoint.py) actually
recovers.

A robustness claim without a way to trigger the failure is a
docstring; this module makes every cell of the crash matrix
(tools/crash_matrix.py) a *seeded, reproducible* event:

* **process kill at a chunk boundary** — the engine calls
  :func:`fire` at the two seams a real preemption lands on (the
  per-chunk sync boundary, and mid-chunk between dispatch and the
  stats readback); an armed ``kill`` fault ``os._exit``\\ s there, the
  way a preempted VM or an OOM-killer does (no atexit, no flushed
  trace — the resumed process's artifacts are the record, exactly as
  in production);
* **mid-chunk device exception** — an armed ``raise`` fault throws
  :class:`InjectedFault` at the same seams, modeling a device error
  surfacing through the XLA dispatch/readback path; the supervisor
  (checkpoint.supervised_run) treats it like any other device fault
  and retries from the last snapshot;
* **torn / corrupt snapshot** — :func:`corrupt_snapshot` truncates or
  bit-flips a written snapshot file, which resume must *detect*
  (zip CRC or the manifest's per-buffer checksum) and refuse with
  ``SnapshotCorruptError`` — never a silent wrong answer;
* **stale manifest** — :func:`stale_manifest` rewrites the snapshot's
  manifest (wrong git SHA, wrong encoding fingerprint) with VALID
  buffer checksums, which resume must refuse with
  ``SnapshotStaleError``;
* **persistent per-shard device fault** (the degrade-and-continue
  round) — a ``shard_fault`` armed with a shard id raises
  :class:`InjectedShardFault` at EVERY chunk at or past its armed
  chunk *as long as the faulted shard is still in the run's mesh*
  (the engines pass their live shard-id set to :func:`fire`): the
  model of a chip that died and stays dead. The supervisor's
  :class:`~stateright_tpu.checkpoint.FailurePolicy` sees the same
  shard fail across retries, classifies it persistent, and degrades
  the run onto the surviving shards — after which the fault stops
  firing, exactly as a dropped chip stops mattering;
* **chunk-dispatch hang** — a ``hang`` sleeps ``hang_sec`` (default
  30 s) at the dispatch site instead of raising: the XLA:CPU
  thunk-runtime livelock family's shape (ROADMAP §carried), which no
  exception path ever surfaces. Only the hung-dispatch watchdog
  (checkers/tpu.py, ``watchdog_factor``) can see it;
* **collective-seam raise** — a ``raise`` armed at the
  ``collective_seam`` site fires only on mesh engines, just before
  the sharded dispatch: a device error surfacing from the all_to_all
  path, which the supervisor must treat like any chunk fault.

Faults arm either programmatically (:func:`arm`, in-process tests) or
via the ``STPU_FAULTS`` environment variable (subprocess kill cells):
a comma-separated list of ``<action>@<site>:<chunk>[:<arg>]`` specs,
e.g. ``STPU_FAULTS="kill@chunk_boundary:2"``,
``STPU_FAULTS="raise@mid_chunk:1"``,
``STPU_FAULTS="hang@mid_chunk:1:20"`` (arg = seconds), or
``STPU_FAULTS="shard_fault@mid_chunk:1:0"`` (arg = shard id). Sites
are ``chunk_boundary`` (fires AFTER the chunk's snapshot write, so a
kill there proves the committed-snapshot sequencing), ``mid_chunk``
(fires after the async dispatch, before the stats readback), and
``collective_seam`` (mesh engines only, before the sharded dispatch).
Each armed fault fires ONCE by default, so a supervised retry doesn't
re-trip it — except ``shard_fault``, which is persistent by design.

Every firing emits a ``fault_injected`` telemetry event (best effort:
a ``kill`` loses the in-memory trace with the process, as a real kill
would). Import-light (stdlib only) so tools and tests load it without
jax.
"""

from __future__ import annotations

import os
from typing import Optional

SITES = ("chunk_boundary", "mid_chunk", "collective_seam")
ACTIONS = ("raise", "kill", "hang", "shard_fault")

#: exit code of an injected process kill (mirrors SIGKILL's 128+9 so
#: drivers distinguish the injected death from an assertion failure).
KILL_EXIT_CODE = 137

#: default sleep of an injected dispatch hang (long enough that any
#: sanely derived watchdog deadline expires first; a daemonized hang
#: thread dies with the process, so a recovered run never waits it
#: out).
DEFAULT_HANG_SEC = 30.0


class InjectedFault(RuntimeError):
    """A deterministically injected fault (``raise`` action). Carries
    the site and chunk so the supervisor's recovery warning names what
    fired. Deliberately NOT matched by the auto-budget retry (its
    message never mentions a buffer overflow): injected faults are the
    supervisor's to handle."""

    def __init__(self, site: str, chunk: int):
        super().__init__(
            f"injected fault at {site} (chunk {chunk}) — "
            "stateright_tpu/faultinject.py"
        )
        self.site = site
        self.chunk = chunk


class InjectedShardFault(InjectedFault):
    """A persistent per-shard device fault (``shard_fault`` action):
    the model of one dead chip in a mesh. Carries the shard id so the
    supervisor's :class:`~stateright_tpu.checkpoint.FailurePolicy`
    can attribute repeated failures to the same shard and escalate to
    an elastic degrade."""

    def __init__(self, site: str, chunk: int, shard: int):
        RuntimeError.__init__(
            self,
            f"injected persistent device fault on shard {shard} at "
            f"{site} (chunk {chunk}) — stateright_tpu/faultinject.py"
        )
        self.site = site
        self.chunk = chunk
        self.shard = int(shard)


_ARMED: list[dict] = []
_ENV_PARSED = False


def parse_spec(spec: str) -> dict:
    """One ``<action>@<site>:<chunk>[:<arg>]`` spec -> an armed-fault
    dict. The optional trailing arg is the hang duration in seconds
    (``hang``) or the faulted shard id (``shard_fault``)."""
    try:
        action, rest = spec.split("@", 1)
        parts = rest.split(":")
        site = parts[0]
        chunk_i = int(parts[1])
        arg = parts[2] if len(parts) > 2 else None
        if len(parts) > 3:
            raise ValueError("too many fields")
    except (ValueError, IndexError) as exc:
        raise ValueError(
            f"bad fault spec {spec!r} (want "
            "<action>@<site>:<chunk>[:<arg>], e.g. "
            "kill@chunk_boundary:2, hang@mid_chunk:1:20, "
            "shard_fault@mid_chunk:1:0)"
        ) from exc
    if action not in ACTIONS:
        raise ValueError(f"unknown fault action {action!r} (use one of "
                         f"{ACTIONS})")
    if site not in SITES:
        raise ValueError(f"unknown fault site {site!r} (use one of "
                         f"{SITES})")
    f = dict(action=action, site=site, chunk=chunk_i, once=True)
    if action == "hang":
        f["hang_sec"] = (float(arg) if arg is not None
                         else DEFAULT_HANG_SEC)
    elif action == "shard_fault":
        # persistent by design: a dead chip stays dead until the run
        # degrades away from it
        f["shard"] = int(arg) if arg is not None else 0
        f["once"] = False
    elif arg is not None:
        raise ValueError(
            f"fault spec {spec!r}: trailing arg is only meaningful "
            "for hang (seconds) and shard_fault (shard id)"
        )
    return f


def arm(action: str, site: str, chunk: int, once: bool = True,
        shard: Optional[int] = None,
        hang_sec: Optional[float] = None) -> None:
    """Arm one fault programmatically (tests / the crash matrix).
    ``shard_fault`` faults are always persistent — a dead chip stays
    dead until the run degrades away from it (``once`` is ignored)."""
    if action not in ACTIONS:
        raise ValueError(f"unknown fault action {action!r}")
    if site not in SITES:
        raise ValueError(f"unknown fault site {site!r}")
    f = dict(action=action, site=site, chunk=int(chunk), once=once)
    if action == "shard_fault":
        f["shard"] = int(shard or 0)
        f["once"] = False
    if action == "hang":
        f["hang_sec"] = float(
            hang_sec if hang_sec is not None else DEFAULT_HANG_SEC
        )
    _ARMED.append(f)


def disarm_all() -> None:
    """Clear every armed fault (test teardown)."""
    _ARMED.clear()


def armed() -> list[dict]:
    """The currently armed faults (read-only copies)."""
    _parse_env()
    return [dict(f) for f in _ARMED]


def _parse_env() -> None:
    global _ENV_PARSED
    if _ENV_PARSED:
        return
    _ENV_PARSED = True
    env = os.environ.get("STPU_FAULTS", "").strip()
    if not env:
        return
    for spec in env.split(","):
        spec = spec.strip()
        if spec:
            _ARMED.append(parse_spec(spec))


def chunk_for_seed(seed: int, n_chunks: int) -> int:
    """Deterministic chunk pick for a seeded matrix cell: an LCG step
    over the seed folded into [0, n_chunks) — stable across platforms
    (no RNG library), so ``crash_matrix --seed`` reproduces the exact
    kill point."""
    if n_chunks <= 0:
        return 0
    return (seed * 1103515245 + 12345) % n_chunks


def fire(site: str, chunk: int, shards=None) -> None:
    """The engine-side hook (checkers/tpu.py chunk loop): fires the
    first armed fault matching (site, chunk). ``raise`` throws
    :class:`InjectedFault`; ``kill`` emits the telemetry event (lost
    with the process, as a real kill's would be) and ``os._exit``\\ s
    with :data:`KILL_EXIT_CODE`; ``hang`` sleeps its armed duration
    (the watchdog's territory — no exception ever surfaces);
    ``shard_fault`` raises :class:`InjectedShardFault` at EVERY chunk
    at or past its armed chunk, as long as its shard id appears in
    ``shards`` (the engine's live shard-id set — None means
    single-chip/unfiltered, where shard 0 is the only shard). No
    armed faults = a list check and out (the hook is per-chunk, not
    per-wave — cost is noise)."""
    _parse_env()
    if not _ARMED:
        return
    for f in _ARMED:
        if f["site"] != site:
            continue
        if f["action"] == "shard_fault":
            # persistent: the chunk is a first-fire threshold, and a
            # degraded mesh that dropped the shard stops matching
            if chunk < f["chunk"]:
                continue
            if shards is not None and f["shard"] not in shards:
                continue
            if shards is None and f["shard"] != 0:
                continue
        elif f["chunk"] != chunk:
            continue
        if f["once"]:
            _ARMED.remove(f)
        from . import telemetry

        telemetry.emit(
            "fault_injected", site=site, chunk=int(chunk),
            action=f["action"],
            **({"shard": f["shard"]}
               if f["action"] == "shard_fault" else {}),
        )
        if f["action"] == "kill":
            # A real preemption: no cleanup, no atexit, no flushed
            # buffers. os._exit is the honest model.
            os._exit(KILL_EXIT_CODE)
        if f["action"] == "hang":
            # the livelock shape: the dispatch wedges, nothing raises
            import time

            time.sleep(f["hang_sec"])
            return
        if f["action"] == "shard_fault":
            raise InjectedShardFault(site, chunk, f["shard"])
        raise InjectedFault(site, chunk)


# -- snapshot-damage helpers (the torn/stale matrix cells) ----------------


def corrupt_snapshot(path: str, mode: str = "truncate",
                     seed: int = 0) -> None:
    """Damage a written snapshot in place, deterministically:

    * ``truncate`` — keep only the first half of the file (a crash
      mid-write on a filesystem without the atomic-rename guarantee,
      or a partial copy);
    * ``flip`` — flip bits at several seed-jittered offsets across
      the MIDDLE HALF of the file (silent media corruption; buffer
      payloads dominate a snapshot, so the flips land in checksummed
      data — a flip in the zip's redundant structural bytes alone
      would be semantically harmless, which is not the cell this
      models).

    Resume must detect either (``SnapshotCorruptError``)."""
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as fh:
            fh.truncate(max(size // 2, 1))
        return
    if mode == "flip":
        base = size // 4 + (seed * 2654435761) % max(size // 16, 1)
        step = max(size // 16, 1)
        with open(path, "r+b") as fh:
            for k in range(8):
                off = min(base + k * step, size - 1)
                fh.seek(off)
                b = fh.read(1)
                fh.seek(off)
                fh.write(bytes([b[0] ^ 0x10]))
        return
    raise ValueError(f"unknown corruption mode {mode!r} "
                     "(use truncate|flip)")


def stale_manifest(path: str, field: str = "git_sha",
                   value: Optional[str] = None) -> None:
    """Rewrite a snapshot's manifest field (buffer checksums stay
    VALID — this is the stale cell, not the torn cell): ``git_sha``
    models resuming onto a different commit, ``encoding`` models
    resuming into a different model/encoding. Resume must refuse with
    ``SnapshotStaleError``."""
    from . import checkpoint

    manifest, buffers = checkpoint._read_raw(path)
    if field == "git_sha":
        manifest["git_sha"] = value or "0" * 40
    elif field == "encoding":
        manifest["encoding"] = value or "bogus-encoding/W0/K0"
    else:
        raise ValueError(f"unknown stale field {field!r} "
                         "(use git_sha|encoding)")
    checkpoint._write_file(path, manifest, buffers)
