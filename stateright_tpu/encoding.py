"""Fixed-width state encodings: the bridge from models to the TPU engine.

The reference's north star calls for a ``#[derive(TpuState)]``-style
mapping from model states to fixed-width vectors so successor
generation runs as a vmapped pure function (BASELINE.json). This module
defines that contract: an :class:`EncodedModel` pairs a host
:class:`~stateright_tpu.model.Model` (the semantic ground truth and
replay oracle) with

* a ``uint32[width]`` state layout,
* a pure, jax-traceable ``step_vec`` producing all (padded) successors
  of one state at once, and
* vectorized property / boundary predicates aligned index-for-index
  with the host model's ``properties()``.

Dynamic host structures map to bounded canonical device forms
(SURVEY.md §7 step 2): message multisets become count-lane rows or
bitmasks kept in sorted order, FIFO channels become fixed rings, timer
sets become bitmasks — so that equal host states encode to equal
vectors and fingerprint identically.
"""

from __future__ import annotations

from typing import Any, Protocol, Sequence, runtime_checkable

import numpy as np

from .model import Model


@runtime_checkable
class EncodedModel(Protocol):
    #: uint32 lanes per state
    width: int
    #: padded successor count per state (static K)
    max_actions: int
    #: the semantic ground truth; also supplies properties() and replay
    host_model: Model

    def init_vecs(self) -> np.ndarray:
        """uint32[N0, width] — encoded init states (host-side numpy)."""
        ...

    def step_vec(self, vec: Any) -> tuple[Any, ...]:
        """Pure jax function on ONE encoded state:
        ``uint32[width] -> (uint32[max_actions, width], bool[max_actions])``.
        The engine vmaps this over the frontier.

        An encoding with internal capacity bounds (e.g. the compiled
        actor encoding's 8-bit envelope counts) MAY return a third
        element: a scalar ``bool`` that is True when an otherwise-valid
        successor was pruned by such a bound. Engines carry the flag to
        the host and raise — a truncated space is never silently
        reported as fully verified."""
        ...

    def property_conditions_vec(self, vec: Any) -> Any:
        """Pure jax function: ``uint32[width] -> bool[P]`` — the truth of
        each host property's condition at this state, in
        ``host_model.properties()`` order.

        Contract note: the device engines track EventuallyBits in a
        uint32 lane, so EVENTUALLY properties must sit at indices < 32
        of ``properties()`` (order ALWAYS/SOMETIMES after them if
        needed). Every engine validates this at spawn and raises."""
        ...

    def within_boundary_vec(self, vec: Any) -> Any:
        """Pure jax function: ``uint32[width] -> bool``."""
        ...

    def encode(self, state: Any) -> np.ndarray:
        """Host state -> uint32[width]; must be canonical (equal states
        encode equal) and consistent with ``step_vec`` — the engine
        replays counterexample traces through the host model and
        matches fingerprints of encoded successors."""
        ...


@runtime_checkable
class SparseEncodedModel(Protocol):
    """Optional extension of :class:`EncodedModel`: sparse action
    dispatch (PERF.md §paxos).

    The dense ``step_vec`` contract pays for all ``max_actions`` slots
    on every frontier row; for envelope-encoded actor models most slots
    are invalid (paxos check 3: ~200x padding). An encoding providing
    this interface lets the sort-merge engine pre-compact the enabled
    (row, slot) pairs — a cheap elementwise predicate, a per-row bitmap
    extraction, and one small sort — and run the (table-driven)
    transition only on real candidates, mirroring the reference's
    enabled-actions-only enumeration (src/actor/model.rs:243-286).

    Contract (engine-checked by differential tests, not at runtime):

    * ``enabled_mask_vec(vec)[k]`` must equal ``step_vec(vec)[1][k]``
      for every slot ``k`` (the engine applies ``within_boundary_vec``
      to successors itself).
    * ``step_slot_vec(vec, k)`` must equal ``step_vec(vec)[0][k]``
      whenever slot ``k`` is enabled.

    Optional extension — PACKED mask words: an encoding MAY also
    provide ``enabled_bits_vec(vec) -> uint32[ceil(max_actions/32)]``,
    the same mask as bitmap words in the ops/bitmask.py layout (slot
    ``k`` at bit ``k % 32`` of word ``k // 32``, zero tail). When
    present, the engines consume the words directly — the dense
    ``bool[K]`` mask and its packing pass never materialize, and the
    per-row enabled counts come from popcount. It must satisfy
    ``words_to_mask(enabled_bits_vec(vec)) == enabled_mask_vec(vec)``
    (the compiled actor codegen derives the dense view from the words,
    so the two cannot drift; tests/test_codegen_shapes.py pins the
    words path gather-free). Absence is fine: hand encodings that only
    build the dense mask are packed by the engine via
    ``ops.bitmask.mask_to_words``.
    """

    def enabled_mask_vec(self, vec: Any) -> Any:
        """Pure jax function: ``uint32[width] -> bool[max_actions]`` —
        which action slots are enabled at this state. Must be CHEAP
        (field extracts and compares; no successor construction): it
        runs on every (row, slot) cell each wave."""
        ...

    def step_slot_vec(self, vec: Any, slot: Any) -> Any:
        """Pure jax function: ``(uint32[width], uint32 slot) ->
        uint32[width]`` — the successor for one enabled (state, slot)
        pair, with ``slot`` a traced index. Runs only on compacted
        pairs; table gathers by ``slot`` are the intended idiom.

        MAY instead return ``(succ, trunc)`` or ``(succ, trunc,
        hard_trunc)``: ``trunc`` marks pairs pruned by an internal
        encoding bound — excluded from candidates and raised as
        truncation when the successor is IN boundary (the dense
        third-element contract); ``hard_trunc`` marks pairs whose
        successor is unrepresentable outright (e.g. an un-harvested
        history transition) — excluded and raised UNCONDITIONALLY,
        because the garbage successor cannot be trusted even to
        evaluate the boundary."""
        ...


@runtime_checkable
class SymmetricEncodedModel(Protocol):
    """Optional extension of :class:`EncodedModel`: device symmetry
    reduction (ops/canonical.py).

    An encoding whose interchangeable participants occupy uniformly
    strided bit-fields declares the layout as a ``DeviceRewriteSpec``;
    the wave engines then canonicalize every candidate block before
    the fingerprint fold, so the visited key is the canonical
    fingerprint while the frontier keeps the concrete states —
    counterexample paths stay replayable, exactly the host DFS split
    (dfs.rs:300-311). Everything downstream of the fingerprint — the
    sharded ``(owner, fp)`` seam, tiered spills, checkpoints — then
    operates on the reduced space without knowing symmetry exists.

    The spec MUST be a perfect canonicalizer (constant on orbits):
    sort on the FULL per-member tuple, not a subset — see the
    symmetry.py module docstring for why a partial sort key makes the
    visited count search-order-dependent. A declared spec is not
    taken on faith: the reduction soundness analyzer
    (analysis/soundness.py) proves its obligations at spawn and the
    engines refuse an uncertifiable spec with the failed obligation
    (``--unsound-ok`` waives)."""

    def device_rewrite_spec(self):
        """``DeviceRewriteSpec`` for this encoding's interchangeable
        limb group, or None when the instance has none (e.g. a
        single-member configuration)."""
        ...


def device_rewrite_spec(enc):
    """The encoding's ``DeviceRewriteSpec``, or None when it declares
    none — the engines' single capability probe."""
    fn = getattr(enc, "device_rewrite_spec", None)
    return fn() if callable(fn) else None


def ample_mask_host(enc):
    """The encoding's host-precomputed ample-set slot mask
    (``uint32[ceil(max_actions/32)]``, ops/bitmask.py word layout), or
    None when it declares none. The sparse engines AND the words into
    every row's enabled bits — a static partial-order-reduction
    filter. Since round 21 the soundness argument for the dropped
    slots is CHECKED, not trusted: the analyzer
    (analysis/soundness.py) proves enabledness-preservation and
    non-suppression per mask, and the engines refuse an
    uncertifiable mask at program-build time (see
    models/two_phase_commit_tpu.py for the prose version the
    analyzer replaced)."""
    fn = getattr(enc, "ample_mask_host", None)
    if not callable(fn):
        return None
    words = fn()
    if words is None:
        return None
    return np.asarray(words, dtype=np.uint32)


# -- transposed ([W, N]) invocation adapters (PERF.md §layout) -------------
#
# The sort-merge engines keep resident state column-major ``[W, N]``
# (minor dim = rows, so TPU tile padding is negligible and every
# elementwise/fold pass streams lane ROWS). Encodings stay written
# per-state — ``vec[i]`` lane reads, 1-D guard math — and these
# adapters give the engines the transposed batched view without any
# data movement: ``jax.vmap`` over axis 1 turns each per-state lane
# read into a contiguous row slice of the ``[W, N]`` block. Boundary
# transposes (host upload/download, the table-gather seams where
# row-major genuinely wins) stay in the engines; everything here is
# pure batching.

def enabled_bits_cols(enc, states_t: Any) -> Any:
    """``uint32[W, N] -> uint32[N, L]`` — the word-native enabled
    mask over a transposed frontier block (lane reads are row
    slices; the word output stays row-major, it is L≤12 lanes)."""
    import jax

    return jax.vmap(enc.enabled_bits_vec, in_axes=1, out_axes=0)(
        states_t
    )


def enabled_mask_cols(enc, states_t: Any) -> Any:
    """``uint32[W, N] -> bool[N, K]`` — the dense-mask fallback for
    encodings without ``enabled_bits_vec``, transposed invocation."""
    import jax

    return jax.vmap(enc.enabled_mask_vec, in_axes=1, out_axes=0)(
        states_t
    )


def property_conditions_cols(enc, states_t: Any) -> Any:
    """``uint32[W, N] -> bool[N, P]`` over a transposed block."""
    import jax

    return jax.vmap(
        enc.property_conditions_vec, in_axes=1, out_axes=0
    )(states_t)


def within_boundary_cols(enc, succ_t: Any) -> Any:
    """``uint32[W, N] -> bool[N]`` over a transposed successor
    block."""
    import jax

    return jax.vmap(enc.within_boundary_vec, in_axes=1)(succ_t)


def canonicalize_cols(enc, states_t: Any) -> Any:
    """``uint32[W, N] -> uint32[W, N]`` — map each column to its orbit
    representative under the encoding's ``DeviceRewriteSpec``
    (identity passthrough when the encoding declares none). Already
    lane-batched: the kernel is elementwise over lane rows, so no vmap
    is needed."""
    spec = device_rewrite_spec(enc)
    if spec is None:
        return states_t
    import jax.numpy as jnp

    from .ops.canonical import canonicalize_t

    return canonicalize_t(spec, states_t, jnp)


def step_slot_cols_fn(enc, states_axis: int = 0):
    """Build the transposed-successor pair step:
    ``f(states, slots[N]) -> (succ_t uint32[W, N], trunc|None,
    hard|None)``.

    ``states_axis`` picks the INPUT layout: ``0`` takes row-major
    ``[N, W]`` states (the TPU gather seam — on chip, row gathers
    genuinely win and the class prefix transposes once per wave,
    PERF.md §gathers); ``1`` takes column-major ``[W, N]`` states
    (the XLA:CPU engines gather resident columns directly — measured
    faster than the seam transpose + row gather at paxos-4 shapes,
    PERF.md §layout). Either way the successor block assembles
    lane-major, which is exactly the shape the ``[W, N]`` resident
    frontier's class-local ``dynamic_update_slice`` writes and the
    transposed fingerprint fold (``fingerprint_u32v_t``) consume.
    The optional trunc/hard flags stay 1-D ``[N]`` (see
    :class:`SparseEncodedModel`)."""
    import jax
    import jax.numpy as jnp

    res = jax.eval_shape(
        enc.step_slot_vec,
        jax.ShapeDtypeStruct((enc.width,), jnp.uint32),
        jax.ShapeDtypeStruct((), jnp.uint32),
    )
    if isinstance(res, tuple):
        out_axes = (1,) + (0,) * (len(res) - 1)
    else:
        out_axes = 1
    f = jax.vmap(
        enc.step_slot_vec, in_axes=(states_axis, 0),
        out_axes=out_axes,
    )

    def step_cols(states, slots):
        return normalize_step_slot_result(f(states, slots))

    return step_cols


def pair_step_seam(enc, cpu_backend: bool):
    """THE one home of the backend-adaptive pair-state gather-seam
    policy (PERF.md §layout) — both sort-merge engines and
    tools/profile_stages.py build their pair step from here, so the
    policy cannot drift between the engines and the profiler that
    claims to mirror them.

    Returns ``(step_cols, make_pair_states)``:

    * ``step_cols(states, slots)`` — the transposed-successor pair
      step (:func:`step_slot_cols_fn`) in this backend's input
      layout: row states on TPU (row gathers genuinely win there,
      PERF.md §gathers), column states on XLA:CPU;
    * ``make_pair_states(frontier_full, frontier_class_t)`` — builds
      the per-wave ``pair_states(idx) -> uint32[W or n, ...]`` gather
      feeding it. On XLA:CPU it gathers resident COLUMNS off the
      FULL ``[W, F]`` carry buffer (measured faster than the seam
      transpose + row gather at paxos-4 shapes, and the full buffer
      aliases for free as a loop operand); on TPU it transposes the
      CLASS view once per wave and gathers rows.
    """
    step_cols = step_slot_cols_fn(
        enc, states_axis=1 if cpu_backend else 0
    )

    def make_pair_states(frontier_full, frontier_class_t):
        if cpu_backend:
            return lambda idx: frontier_full[:, idx]
        frontier_rows = frontier_class_t.T  # the sanctioned seam copy

        return lambda idx: frontier_rows[idx]

    return step_cols, make_pair_states


def normalize_step_slot_result(res) -> tuple:
    """``step_slot_vec`` results to canonical ``(succ, trunc|None,
    hard_trunc|None)`` (see :class:`SparseEncodedModel` for the three
    accepted shapes). Lives beside the contract so every engine and
    tool interprets encodings identically."""
    if not isinstance(res, tuple):
        return res, None, None
    if len(res) == 2:
        return res[0], res[1], None
    return res


class EncodedModelBase:
    """Convenience defaults."""

    def within_boundary_vec(self, vec):
        return True

    def decode(self, vec) -> Any:
        raise NotImplementedError


def has_trivial_boundary(enc) -> bool:
    """True when ``enc`` has no real boundary predicate — the
    inherited :class:`EncodedModelBase` default, or an encoding-level
    ``trivial_boundary`` flag (e.g. a compiled actor encoding with no
    boundary spec). The single definition every engine's
    skip-the-boundary-pass gate goes through, so the dense and sparse
    paths can't disagree on whether the pass runs."""
    wb = getattr(type(enc), "within_boundary_vec", None)
    return (
        wb is EncodedModelBase.within_boundary_vec
        or bool(getattr(enc, "trivial_boundary", False))
    )
