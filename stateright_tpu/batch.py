"""Multi-tenant wave batching: fuse N admitted sessions into ONE
device dispatch (ROADMAP direction 2(a), PERF.md §batching).

The round-18 resident service made the checker warm, but each admitted
session still owns its waves: the ~106 ms per-chunk sync floor and the
per-dispatch host round-trip are paid once PER QUERY, which dominates
exactly where the serving story lives — small submitted models (2pc
rm=4 settles in 14 waves). The fix is the serving-throughput analogue
of continuous batching in an inference stack: a **session-id lane**
rides alongside the existing (owner, fp) routing, so one wave-program
dispatch advances the frontiers of N compatible sessions at once and
the sync floor amortizes 1/N per session.

Exactness comes from the same partition argument the mesh shards use:
the sid limb is part of every fused state vector, so per-session
visited prefixes and parent-log segments are **disjoint by
sid-partition** — a fingerprint never crosses sessions, exactly as it
never crosses shards. Counts, verdicts, and counterexample paths are
therefore per-session facts the fused run computes bit-identically to
a solo run (tests/test_serve.py pins 16,668 / 1,568 and trace_diff
zero counter divergence batched-vs-solo).

Layering:

* :class:`FusedEncodedModel` / :class:`FusedModel` — the sid-lane
  product encoding: member state vectors padded to a common width with
  the sid in the LAST limb, ``step_vec`` dispatched per-row by
  ``lax.switch``, property conditions vacuous off-lane (ALWAYS → True,
  SOMETIMES → False), so one fused property list concatenates the
  members' lists with zero cross-talk.
* :class:`FusedWaveChecker` — the hash wave engine
  (checkers/tpu.py) extended through its four fused-engine seams
  (``_seed_extra`` / ``_body_extra`` / ``_stats_extra`` /
  ``_on_chunk_stats``): per-session unique/depth/generated counters and
  a per-wave per-session lane log ride the device carry and come back
  in the SAME packed per-chunk stats readback — no extra sync. A
  session whose lane settles (all its properties discovered, or its
  lane frontier drains) has its rows masked dead in the very next
  wave, so a settling session never holds the others' waves.
* :class:`BatchGroup` — the host-side rendezvous the resident service
  (serve.py) slots into its admission and dispatch-gate seams: sessions
  of one compatibility class (:func:`batch_eligible`) join an open
  group for a short window; the first member leads the fused run under
  a throwaway tracer; every settled member is PEELED between chunks —
  its thread wakes immediately, replays its demultiplexed lane view
  into its own session tracer (zero cross-session bleed), and returns
  its verdict while the batch keeps running. Anything that cannot fuse
  (no peers, fused plan over budget, fused dispatch error) falls back
  to the round-18 solo FIFO path with a one-line reason.

Telemetry demux contract: a member's replayed trace is a valid solo
trace — wave rows satisfy the running unique_total check, verdicts land
at their true settle chunk, ``latency_profile`` derives from the
replayed chunk events (each carrying this session's 1/N_active share of
the fused dispatch+sync walls), and ``trace_diff`` against a solo run
of the same model shows zero counter divergence. The fused compile is
ledger-attributed via re-emitted ``program_build`` rows with
1/N-amortized walls and a ``batch`` marker.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

import numpy as np

from .checker import CheckerBuilder
from .checkers.tpu import TpuBfsChecker, _fp_int
from .encoding import EncodedModelBase, has_trivial_boundary
from .model import Expectation, Model, Property
from .path import Path

#: per-wave per-session lane-log fields (the sid-partitioned analogue
#: of telemetry.WAVE_LOG_FIELDS, minus the fields a lane cannot own):
#: frontier rows, candidates, new states, cumulative unique, depth
#: entering the wave.
LANE_LOG_FIELDS = 5


def _pow2ceil(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


# -- the sid-lane product encoding ----------------------------------------


class FusedModel(Model):
    """Host-side product of N member models, disjoint by sid: states
    are ``(sid, member_state)``, actions/successors delegate to the
    owning member, and the property list concatenates the members'
    lists under ``s{i}:`` name prefixes with off-lane-vacuous
    conditions. This is the replay oracle the fused engine decodes
    counterexample paths through; stripping the sid from a decoded
    path yields the member's own path."""

    def __init__(self, member_models: list):
        self.members = list(member_models)
        self._props = []
        for i, m in enumerate(self.members):
            for p in m.properties():
                self._props.append(Property(
                    p.expectation,
                    f"s{i}:{p.name}",
                    self._lane_condition(i, m, p),
                ))

    @staticmethod
    def _lane_condition(i: int, member, prop):
        vac = prop.expectation == Expectation.ALWAYS

        def cond(model, st):
            if st[0] != i:
                return vac
            return prop.condition(member, st[1])

        return cond

    def init_states(self):
        return [
            (i, s)
            for i, m in enumerate(self.members)
            for s in m.init_states()
        ]

    def actions(self, state):
        sid, st = state
        return self.members[sid].actions(st)

    def next_state(self, state, action):
        sid, st = state
        nxt = self.members[sid].next_state(st, action)
        return None if nxt is None else (sid, nxt)

    def properties(self):
        return list(self._props)

    def format_action(self, action):
        return str(action)


class FusedEncodedModel(EncodedModelBase):
    """Device-side product encoding: member vectors padded to
    ``max(width) + 1`` lanes with the session id in the LAST limb.
    The sid limb is fingerprinted with the rest of the state, so fused
    visited keys are sid-partitioned — a fingerprint never crosses
    sessions, exactly as it never crosses mesh shards.

    ``step_vec`` dispatches per-row by ``lax.switch`` on the sid limb;
    each branch pads its member's ``[K_i, W_i]`` successor block into
    the fused ``[K_f, W_f]`` shape and stamps the sid on every row.
    Property conditions evaluate every member's predicate (pure masked
    math) and select the on-lane one, with off-lane slots vacuous
    (ALWAYS → True so it can never fire off-lane; SOMETIMES → False so
    it can never be satisfied off-lane). Members must have trivial
    boundaries (:func:`batch_eligible` enforces it) so the fused
    encoding's inherited trivial boundary is exact."""

    def __init__(self, member_encs: list, host_model: FusedModel):
        self.members = list(member_encs)
        self.host_model = host_model
        self.width = max(m.width for m in self.members) + 1
        self.max_actions = max(m.max_actions for m in self.members)
        #: off-lane truth per member property (ALWAYS → True), in
        #: member property order — the vacuity vector step 2 selects.
        self._off_lane = [
            np.array(
                [p.expectation == Expectation.ALWAYS
                 for p in m.host_model.properties()],
                dtype=bool,
            )
            for m in self.members
        ]

    # -- host side ---------------------------------------------------------

    def init_vecs(self) -> np.ndarray:
        rows = []
        for i, m in enumerate(self.members):
            iv = np.asarray(m.init_vecs(), np.uint32).reshape(
                -1, m.width
            )
            pad = np.zeros((iv.shape[0], self.width), np.uint32)
            pad[:, : m.width] = iv
            pad[:, self.width - 1] = i
            rows.append(pad)
        return np.concatenate(rows, axis=0)

    def encode(self, state) -> np.ndarray:
        sid, st = state
        m = self.members[sid]
        row = np.zeros(self.width, np.uint32)
        row[: m.width] = np.asarray(m.encode(st), np.uint32)
        row[self.width - 1] = sid
        return row

    def cache_key(self):
        """Composite program-cache identity: the fused program is a
        function of every member's encoding identity and shape plus
        the fusion arity."""
        parts = []
        for m in self.members:
            key = m.cache_key() if hasattr(m, "cache_key") else None
            parts.append(
                (type(m).__name__, key, m.width, m.max_actions)
            )
        return ("fused", len(self.members), tuple(parts))

    # -- device side -------------------------------------------------------

    def step_vec(self, vec):
        import jax
        import jax.numpy as jnp

        Wf, Kf = self.width, self.max_actions
        sid = vec[Wf - 1]

        def branch(i, m):
            def f(v):
                res = m.step_vec(v[: m.width])
                if isinstance(res, tuple) and len(res) == 3:
                    succs, valid, trunc = res
                else:
                    succs, valid = res
                    trunc = jnp.bool_(False)
                out = jnp.zeros((Kf, Wf), jnp.uint32)
                out = out.at[: m.max_actions, : m.width].set(succs)
                out = out.at[:, Wf - 1].set(jnp.uint32(i))
                val = jnp.zeros((Kf,), bool)
                val = val.at[: m.max_actions].set(valid)
                return out, val, jnp.asarray(trunc, bool)

            return f

        branches = [branch(i, m) for i, m in enumerate(self.members)]
        idx = jnp.clip(
            sid.astype(jnp.int32), 0, len(branches) - 1
        )
        return jax.lax.switch(idx, branches, vec)

    def property_conditions_vec(self, vec):
        import jax.numpy as jnp

        sid = vec[self.width - 1]
        out = []
        for i, m in enumerate(self.members):
            conds = m.property_conditions_vec(vec[: m.width])
            off = jnp.asarray(self._off_lane[i])
            on = sid == jnp.uint32(i)
            out.append(jnp.where(on, conds, off))
        return jnp.concatenate(out)


# -- eligibility / compatibility classes ----------------------------------


def batch_eligible(checker) -> tuple:
    """``(class_key, None)`` when ``checker`` can join a fused batch,
    else ``(None, reason)`` with a one-line human reason (the FIFO
    fallback message). Two sessions may fuse iff their class keys are
    equal — the class groups sessions whose padded shapes are close
    (pow2-bucketed width / action fan-out), so fusion never pays
    unbounded padding for a mismatched pair."""
    if not isinstance(checker, TpuBfsChecker):
        return None, "not a device wave engine"
    if getattr(checker, "mesh", None) is not None or getattr(
        checker, "n_shards", 1
    ) not in (None, 1):
        return None, "sharded mesh sessions batch per-shard already"
    b = checker.builder
    if b._visitor is not None:
        return None, "visitor sessions cannot batch"
    if b._target_state_count is not None or \
            b._target_max_depth is not None:
        return None, "bounded-target sessions cannot batch"
    if not checker.track_paths:
        return None, "untracked-path sessions cannot batch"
    if checker.checkpoint_every:
        return None, "checkpointing sessions cannot batch"
    if getattr(checker, "_resume", None) is not None:
        return None, "warm-started sessions resume solo"
    if getattr(checker, "tier_hot_rows", None):
        return None, "tiered sessions cannot batch"
    # Symmetry is a shape-compatibility property, not a padding one: a
    # reduced session's visited keys are canonical fingerprints while a
    # raw session's are plain, so fusing them would mix incomparable key
    # spaces in one visited set. The fused engine is the hash wave
    # engine, which has no canonicalization pass at all — refuse both
    # modes outright rather than minting a class nobody can serve.
    # (Any checker that reaches here with sym_spec set already passed
    # the soundness-certificate gate at spawn, analysis/soundness.py —
    # batching never has to re-litigate reduction soundness.)
    if getattr(checker, "sym_spec", None) is not None:
        return None, "symmetry-reduced sessions cannot fuse (canonical" \
            " keys are a different compatibility class)"
    if getattr(checker, "ample_set", False):
        return None, "ample-set filtered sessions cannot fuse (reduced" \
            " action sets are a different compatibility class)"
    enc = checker.encoded
    if not hasattr(enc, "cache_key"):
        return None, "encoding lacks a cache_key identity"
    if not has_trivial_boundary(enc):
        return None, "bounded-boundary encodings cannot batch"
    for p in checker.model.properties():
        if p.expectation == Expectation.EVENTUALLY:
            return None, "eventually properties cannot batch"
    key = (
        "batch",
        _pow2ceil(enc.width),
        _pow2ceil(enc.max_actions),
    )
    return key, None


# -- the fused engine ------------------------------------------------------


class FusedWaveChecker(TpuBfsChecker):
    """The hash wave engine over the sid-lane product encoding, with
    per-session lane accounting riding the existing per-chunk stats
    readback. Extra packed-stat layout after the per-property
    discovery lanes (``s[11 + 3P:]``):

    ``[N unique][N depth][N gen][waves_per_sync * N * 5 lane log]``

    where a lane-log row is ``LANE_LOG_FIELDS`` = (frontier rows,
    candidates, new states, cumulative unique, depth entering the
    wave) — exactly the engine-independent BFS facts trace_diff
    compares (DIFF_COUNTERS), so a lane's rows reproduce a solo run's
    wave counters bit-exactly."""

    def __init__(self, member_checkers: list,
                 waves_per_sync: Optional[int] = None):
        members = list(member_checkers)
        if len(members) < 2:
            raise ValueError("a fused batch needs >= 2 sessions")
        fm = FusedModel([c.model for c in members])
        fe = FusedEncodedModel([c.encoded for c in members], fm)
        super().__init__(
            CheckerBuilder(fm),
            encoded=fe,
            # 4x the summed member capacities keeps the fused hash
            # table's occupancy in the flat probe regime even when
            # every member fills its own capacity.
            capacity=_pow2ceil(
                4 * sum(c.capacity for c in members)
            ),
            frontier_capacity=_pow2ceil(
                sum(c.frontier_capacity for c in members)
            ),
            track_paths=True,
            waves_per_sync=(
                waves_per_sync
                or min(c.waves_per_sync for c in members)
            ),
            cand_capacity=None,
        )
        self.n_sessions = len(members)
        #: per-member slice into the fused property list
        self.lane_slices: list[slice] = []
        off = 0
        for c in members:
            n = len(c.model.properties())
            self.lane_slices.append(slice(off, off + n))
            off += n
        #: host-side per-chunk lane observations (_on_chunk_stats)
        self.chunk_records: list[dict] = []
        #: optional callable(record) invoked at every chunk sync —
        #: the BatchGroup peel hook
        self.on_chunk: Optional[Callable[[dict], None]] = None
        self._lane_prev_waves = 0
        self._final_lanes: Optional[dict] = None

    def _cache_extras(self) -> tuple:
        return ("fused", self.n_sessions)

    # -- device program extensions ----------------------------------------

    def _seed_extra(self, out, init_rows, jnp) -> dict:
        N = self.n_sessions
        W = self.encoded.width
        sid = init_rows[:, W - 1].astype(jnp.int32)
        counts = jnp.zeros(N, jnp.uint32).at[sid].add(1)
        return dict(
            sid_unique=counts,
            sid_depth=jnp.ones(N, jnp.uint32),
            sid_gen=counts,
            sid_log=jnp.zeros(
                (self.waves_per_sync, N, LANE_LOG_FIELDS),
                jnp.uint32,
            ),
        )

    def _body_extra(self, c, out, ctx, jnp) -> dict:
        N = self.n_sessions
        W = self.encoded.width

        def lane_counts(rows_sid, valid):
            idx = jnp.where(
                valid, rows_sid.astype(jnp.int32), jnp.int32(N)
            )
            return jnp.zeros(N + 1, jnp.uint32).at[idx].add(1)[:N]

        f_rows = lane_counts(c["frontier"][:, W - 1], c["fval"])
        cand = lane_counts(
            ctx["ex"]["flat"][:, W - 1], ctx["ex"]["v"]
        )
        new_per = lane_counts(
            ctx["b_ext"][:, W - 1], ctx["is_new"] & ctx["b_val"]
        )
        sid_unique = c["sid_unique"] + new_per
        sid_gen = c["sid_gen"] + cand

        # per-lane all-discovered (the lane's own early exit — the
        # solo run's ``all_disc`` term, sid-partitioned)
        lane_disc = jnp.stack([
            (jnp.all(out["disc_found"][sl])
             if sl.stop > sl.start else jnp.bool_(False))
            for sl in self.lane_slices
        ])
        lane_cont = (new_per > 0) & ~lane_disc
        sid_depth = jnp.where(
            lane_cont, c["sid_depth"] + 1, c["sid_depth"]
        )

        row = jnp.stack(
            [f_rows, cand, new_per, sid_unique, c["sid_depth"]],
            axis=-1,
        ).astype(jnp.uint32)
        sid_log = jnp.asarray(c["sid_log"]).at[c["wchunk"]].set(row)

        # Settlement masking: a lane whose properties all discovered
        # must not keep exploring (the solo run would have stopped) —
        # kill its rows in the NEXT frontier. Rows of drained lanes
        # die on their own (no successors -> no rows).
        next_sid = jnp.clip(
            out["frontier"][:, W - 1].astype(jnp.int32), 0, N - 1
        )
        fval = out["fval"] & ~lane_disc[next_sid]
        return dict(
            sid_unique=sid_unique,
            sid_depth=sid_depth,
            sid_gen=sid_gen,
            sid_log=sid_log,
            fval=fval,
        )

    def _stats_extra(self, c, jnp) -> list:
        return [
            c["sid_unique"],
            c["sid_depth"],
            c["sid_gen"],
            c["sid_log"].reshape(-1),
        ]

    # -- host-side demux ---------------------------------------------------

    def _lane_stats(self, s: np.ndarray) -> dict:
        N = self.n_sessions
        P = len(self.model.properties())
        base = 11 + 3 * P
        unique = np.array(s[base: base + N], np.int64)
        depth = np.array(s[base + N: base + 2 * N], np.int64)
        gen = np.array(s[base + 2 * N: base + 3 * N], np.int64)
        log = np.array(
            s[base + 3 * N:
              base + 3 * N
              + self.waves_per_sync * N * LANE_LOG_FIELDS],
            np.int64,
        ).reshape(self.waves_per_sync, N, LANE_LOG_FIELDS)
        return dict(
            unique=unique, depth=depth, gen=gen, log=log,
            disc=np.array(s[11: 11 + P], np.int64),
            disc_lo=np.array(s[11 + P: 11 + 2 * P], np.uint32),
            disc_hi=np.array(s[11 + 2 * P: 11 + 3 * P], np.uint32),
        )

    def _on_chunk_stats(self, s, carry, chunk_no, t0, t1,
                        dispatch_sec, fetch_sec) -> None:
        lanes = self._lane_stats(np.asarray(s))
        waves_now = int(s[4])
        n_waves = waves_now - self._lane_prev_waves
        record = dict(
            chunk_no=chunk_no,
            wave0=self._lane_prev_waves,
            n_waves=n_waves,
            t0=t0,
            t1=t1,
            dispatch_sec=dispatch_sec,
            fetch_sec=fetch_sec,
            rows=lanes["log"][:n_waves].copy(),
            unique=lanes["unique"],
            depth=lanes["depth"],
            gen=lanes["gen"],
            disc=lanes["disc"],
            disc_lo=lanes["disc_lo"],
            disc_hi=lanes["disc_hi"],
            done=bool(s[0]),
            carry=carry,
        )
        self._lane_prev_waves = waves_now
        self.chunk_records.append(record)
        cb = self.on_chunk
        if cb is not None:
            cb(record)

    def _consume_extra_stats(self, extra: np.ndarray) -> None:
        N = self.n_sessions
        self._final_lanes = dict(
            unique=np.array(extra[:N], np.int64),
            depth=np.array(extra[N: 2 * N], np.int64),
            gen=np.array(extra[2 * N: 3 * N], np.int64),
        )


# -- the rendezvous / demux machinery -------------------------------------


class BatchMember:
    """One session's seat in a batch group."""

    def __init__(self, index: int, checker, label: str = ""):
        self.index = index
        self.checker = checker
        self.label = label
        self.done = threading.Event()
        #: set when this member settled inside the fused run
        self.payload: Optional[dict] = None
        #: set when this member must run solo instead (one-line reason)
        self.fallback_reason: Optional[str] = None
        #: serve.py installs these: called before the solo fallback
        #: run (the round-18 solo admission), and to surface the
        #: fallback reason on the session's own stdout.
        self.solo_prepare: Optional[Callable[[], None]] = None
        self.notify: Optional[Callable[[str], None]] = None


class BatchGroup:
    """A rendezvous of compatible sessions that fuses into one device
    run. The FIRST member to call :meth:`member_run` leads: it waits
    out the batching window, freezes membership, builds the
    :class:`FusedWaveChecker`, prices it through the injected
    ``admit`` hook, and drives the fused run under a throwaway tracer.
    Every other member blocks on its own event and is woken the moment
    its lane settles (the peel), then replays its demultiplexed lane
    view into its own thread's tracer and returns. Any failure to fuse
    degrades to the solo FIFO path with a one-line reason — fusion is
    an optimization, never a correctness dependency."""

    def __init__(self, group_id: int, class_key, *,
                 max_sessions: int = 4, window_sec: float = 0.25,
                 waves_per_sync: Optional[int] = None,
                 admit: Optional[Callable] = None,
                 make_gate: Optional[Callable] = None):
        self.group_id = group_id
        self.class_key = class_key
        self.max_sessions = max_sessions
        self.window_sec = window_sec
        self.waves_per_sync = waves_per_sync
        self.admit = admit
        self.make_gate = make_gate
        self.members: list[BatchMember] = []
        self.fused: Optional[FusedWaveChecker] = None
        self._lock = threading.Lock()
        self._full = threading.Event()
        self._frozen = False
        self._alive: list[bool] = []
        self._settle_error: Optional[str] = None
        self._lead_tracer = None

    # -- membership --------------------------------------------------------

    def try_join(self, checker, label: str = "") -> Optional[BatchMember]:
        """Claim a seat; None when the group already froze or filled
        (the caller opens a fresh group)."""
        with self._lock:
            if self._frozen or len(self.members) >= self.max_sessions:
                return None
            m = BatchMember(len(self.members), checker, label)
            self.members.append(m)
            if len(self.members) >= self.max_sessions:
                self._full.set()
            return m

    # -- per-session entry point ------------------------------------------

    def member_run(self, member: BatchMember, reporter=None) -> None:
        """Runs on the member's own session thread, replacing its
        checker's ``_run``. The leader (seat 0) drives the fused run;
        followers wait for their peel."""
        if member.index == 0:
            self._lead()
        else:
            member.done.wait()
        if member.fallback_reason is not None:
            if member.notify is not None:
                member.notify(member.fallback_reason)
            if member.solo_prepare is not None:
                member.solo_prepare()
            type(member.checker)._run(member.checker, reporter)
            return
        self._replay(member)

    # -- the leader --------------------------------------------------------

    def _fallback_all(self, members, reason: str) -> None:
        for m in members:
            if not m.done.is_set():
                m.fallback_reason = reason
                m.done.set()

    def _lead(self) -> None:
        self._full.wait(self.window_sec)
        with self._lock:
            self._frozen = True
            members = list(self.members)
        self._alive = [True] * len(members)
        if len(members) < 2:
            self._fallback_all(
                members,
                "batch: no compatible peers arrived within the "
                "batching window; running solo via the FIFO gate",
            )
            return
        try:
            fused = FusedWaveChecker(
                [m.checker for m in members],
                waves_per_sync=self.waves_per_sync,
            )
        except Exception as exc:
            self._fallback_all(
                members,
                f"batch: fused engine construction failed "
                f"({type(exc).__name__}: {exc}); running solo",
            )
            return
        if self.admit is not None:
            reason = self.admit(fused, members)
            if reason:
                self._fallback_all(members, reason)
                return
        if self.make_gate is not None:
            fused.dispatch_gate = self.make_gate()
        fused.on_chunk = self._on_chunk
        self.fused = fused
        from .telemetry import RunTracer

        tracer = RunTracer()
        self._lead_tracer = tracer
        try:
            with tracer.activate_thread():
                fused._ensure_run(None)
        except Exception as exc:
            # members already peeled keep their exact results; the
            # rest degrade to solo
            self._fallback_all(
                members,
                f"batch: fused dispatch failed "
                f"({type(exc).__name__}: {exc}); running solo",
            )
            return
        with self._lock:
            last = len(fused.chunk_records) - 1
            for m in members:
                if not m.done.is_set():
                    self._settle(m, last)

    # -- the peel ----------------------------------------------------------

    def _on_chunk(self, record: dict) -> None:
        """Called at every fused chunk sync (leader thread): wake every
        member whose lane settled in this chunk — the member replays
        and returns while the batch keeps running."""
        with self._lock:
            chunk_idx = len(self.fused.chunk_records) - 1
            for m in self.members:
                if m.done.is_set() or not self._alive[m.index]:
                    continue
                if self._lane_settled(m.index, record):
                    self._alive[m.index] = False
                    self._settle(m, chunk_idx)

    def _lane_settled(self, i: int, record: dict) -> bool:
        if record["done"]:
            return True
        sl = self.fused.lane_slices[i]
        if sl.stop > sl.start and all(record["disc"][sl]):
            return True
        rows = record["rows"][:, i, :]
        live = rows[rows[:, 0] > 0]
        # a live wave that committed nothing drains the lane frontier
        # for good — the lane's exhaustion wave
        return live.size > 0 and int(live[-1][2]) == 0

    def _settle(self, member: BatchMember, chunk_idx: int) -> None:
        # Materialize the parent forest NOW, while the settle chunk's
        # carry buffers are still live — the engine donates them into
        # the next chunk's dispatch. The lane's visited prefix and
        # parent-log segment are complete and immutable at its settle
        # chunk (fingerprints are sid-partitioned), so this snapshot
        # decodes the lane's counterexample paths exactly.
        carry = self.fused.chunk_records[chunk_idx]["carry"]
        forest = {
            k: np.asarray(carry[k])
            for k in ("t_lo", "t_hi", "p_lo_t", "p_hi_t")
        }
        member.payload = dict(
            upto=chunk_idx,
            records=list(self.fused.chunk_records[: chunk_idx + 1]),
            forest=forest,
            # the fused compile's build rows as of this settle — the
            # seed/chunk builds land before the first chunk sync, so
            # even the earliest peel sees them (events append-only)
            builds=[
                dict(e) for e in self._lead_tracer.events
                if e.get("ev") == "program_build"
            ],
        )
        member.done.set()

    # -- the member-side demux --------------------------------------------

    def _replay(self, member: BatchMember) -> None:
        """Replay this member's lane view of the fused run into the
        member checker — on the member's OWN thread, under the
        member's own thread-scoped tracer, so the session trace holds
        only this session's events (zero cross-session bleed). The
        ``batch`` event emitted here is also what the tracer→metrics
        bridge (stateright_tpu/metrics.py ``bridge_events``) folds
        into ``stpu_batched_sessions_total`` and the
        ``stpu_batch_occupancy`` histogram — the live fused-group-size
        signal on ``GET /.metrics``."""
        from . import telemetry

        fused = self.fused
        i = member.index
        checker = member.checker
        payload = member.payload
        records = payload["records"]
        tracer = telemetry.current_tracer()
        n = len(self.members)

        if tracer is not None:
            tracer.event(
                "batch", group=self.group_id, size=n, index=i,
                chunks=len(records),
            )
            for b in payload["builds"]:
                row = {
                    k: v for k, v in b.items()
                    if k not in ("ev", "run", "t")
                }
                for lane in ("wall_sec", "cold_sec"):
                    if row.get(lane):
                        row[lane] = round(row[lane] / n, 6)
                row["batch"] = self.group_id
                tracer.event("program_build", **row)

        lane_waves = 0
        verdicts_pending = {
            gj: checker.model.properties()[gj - self.fused
                                           .lane_slices[i].start]
            for gj in range(self.fused.lane_slices[i].start,
                            self.fused.lane_slices[i].stop)
        }
        emitted = set()
        lat = dict(chunks=0, dispatch_sec=0.0, fetch_sec=0.0,
                   device_sec=0.0, fetch_min=None,
                   t_start=records[0]["t0"] if records else 0.0,
                   t_first_sync=None)
        chunk_out = 0
        for r in records:
            rows = r["rows"][:, i, :]
            live = rows[rows[:, 0] > 0]
            # sessions sharing this chunk's dispatch: each gets a
            # 1/N_active share of its walls (the amortized sync floor)
            n_active = max(
                1,
                int(np.sum(np.any(r["rows"][:, :, 0] > 0, axis=0))),
            )
            share_disp = r["dispatch_sec"] / n_active
            share_fetch = r["fetch_sec"] / n_active
            if live.size > 0:
                wave_rows = [
                    [int(row[0]), 0, int(row[1]), int(row[2]),
                     int(row[3]), int(row[4]), 0, 0]
                    for row in live
                ]
                if tracer is not None:
                    tracer.record_chunk(
                        chunk=chunk_out,
                        wave0=lane_waves,
                        t0=r["t0"],
                        t1=r["t1"],
                        dispatch_sec=share_disp,
                        fetch_sec=share_fetch,
                        n_waves=len(wave_rows),
                        wave_rows=wave_rows,
                        pairs_valid=False,
                    )
                lane_waves += len(wave_rows)
                chunk_out += 1
                lat["chunks"] += 1
                lat["dispatch_sec"] += share_disp
                lat["fetch_sec"] += share_fetch
                if (lat["fetch_min"] is None
                        or share_fetch < lat["fetch_min"]):
                    lat["fetch_min"] = share_fetch
                if lat["t_first_sync"] is None:
                    lat["t_first_sync"] = r["t1"]
            sl = self.fused.lane_slices[i]
            for gj in list(verdicts_pending):
                if r["disc"][gj] and gj not in emitted:
                    emitted.add(gj)
                    prop = verdicts_pending.pop(gj)
                    fp = _fp_int(int(r["disc_lo"][gj]),
                                 int(r["disc_hi"][gj]))
                    checker._discovered_fps[prop.name] = fp
                    if tracer is not None:
                        tracer.event(
                            "verdict",
                            property=prop.name,
                            expectation=prop.expectation.name.lower(),
                            kind="discovery",
                            wave=lane_waves,
                            depth=int(r["depth"][i]),
                            chunk=max(chunk_out - 1, 0),
                        )
            if r is records[-1]:
                break

        final = records[-1]
        checker._total_states = int(final["gen"][i])
        checker._unique_states = int(final["unique"][i])
        checker._max_depth = int(final["depth"][i])
        checker.metrics = dict(
            frontier_size=0,
            occupancy=(checker._unique_states
                       / checker.total_capacity),
            dedup_ratio=(
                1.0 - checker._unique_states / checker._total_states
                if checker._total_states else 0.0
            ),
            waves=lane_waves,
            batch_size=n,
        )
        checker._lat = lat
        checker.memory_plan = fused.memory_plan
        checker._program_key_hash = fused._program_key_hash

        # counterexample paths: decode through the FUSED parent forest
        # (this lane's segment is complete at its settle chunk), then
        # strip the sid — the member path replays on the member model.
        if checker._discovered_fps:
            forest = payload["forest"]
            t_lo, t_hi, p_lo, p_hi = (
                forest[k]
                for k in ("t_lo", "t_hi", "p_lo_t", "p_hi_t")
            )
            occupied = (t_lo != 0) | (t_hi != 0)
            child = (t_hi[occupied].astype(np.uint64) << np.uint64(32)
                     ) | t_lo[occupied].astype(np.uint64)
            parent = (p_hi[occupied].astype(np.uint64) << np.uint64(32)
                      ) | p_lo[occupied].astype(np.uint64)
            generated = {
                int(c): (int(p) if p else None)
                for c, p in zip(child.tolist(), parent.tolist())
            }
            for name, fp in checker._discovered_fps.items():
                fused_path = fused._decode_path(generated, fp)
                checker._discoveries[name] = Path([
                    (st[1], act) for st, act in fused_path.steps
                ])
