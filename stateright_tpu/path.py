"""Counterexample / example paths through a model's state graph.

Mirrors the reference's ``Path<State, Action>`` (stateright
src/checker/path.rs:16-198): a path is a sequence of states joined by
actions. Checkers store only fingerprints on their hot paths (or on
device, for the TPU engine); a ``Path`` is reconstructed afterwards by
*replaying the model* along a fingerprint sequence.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from .fingerprint import fingerprint
from .model import Action, Model, State


class Path:
    """A sequence ``[(state, action_to_next), ..., (final_state, None)]``.

    Matches path.rs:16's ``Vec<(State, Option<Action>)>`` layout.
    """

    def __init__(self, steps: list[tuple[State, Optional[Action]]]):
        if not steps:
            raise ValueError("path cannot be empty")
        self.steps = steps

    @staticmethod
    def from_fingerprints(model: Model, fps: Sequence[int]) -> "Path":
        """Replay ``model`` to recover states/actions from a digest trace.

        Reference: path.rs:20-97, including the panic-with-diagnostic on
        unreplayable traces (a symptom of nondeterministic models whose
        ``actions``/``next_state`` disagree between runs).
        """
        if not fps:
            raise ValueError("empty fingerprint trace")
        state = None
        for init in model.init_states():
            if fingerprint(init) == fps[0]:
                state = init
                break
        if state is None:
            raise RuntimeError(
                f"no init state matches fingerprint {fps[0]:#x}; "
                "is the model deterministic?"
            )
        steps: list[tuple[State, Optional[Action]]] = []
        for next_fp in fps[1:]:
            found = False
            for action in model.actions(state):
                next_state = model.next_state(state, action)
                if next_state is not None and fingerprint(next_state) == next_fp:
                    steps.append((state, action))
                    state = next_state
                    found = True
                    break
            if not found:
                raise RuntimeError(
                    f"no successor of state with fingerprint "
                    f"{fingerprint(state):#x} matches {next_fp:#x}; "
                    "is the model deterministic?"
                )
        steps.append((state, None))
        return Path(steps)

    @staticmethod
    def from_actions(
        model: Model, init_state: State, actions: Sequence[Action]
    ) -> Optional["Path"]:
        """Build a path by applying ``actions`` in order (path.rs:101-131)."""
        steps: list[tuple[State, Optional[Action]]] = []
        state = init_state
        for action in actions:
            next_state = model.next_state(state, action)
            if next_state is None:
                return None
            steps.append((state, action))
            state = next_state
        steps.append((state, None))
        return Path(steps)

    @staticmethod
    def final_state_of(model: Model, fps: Sequence[int]) -> Optional[State]:
        """Replay just far enough to return the last state (path.rs:134-165)."""
        try:
            return Path.from_fingerprints(model, fps).last_state()
        except RuntimeError:
            return None

    # ------------------------------------------------------------------

    def last_state(self) -> State:
        return self.steps[-1][0]

    def states(self) -> list[State]:
        return [s for s, _ in self.steps]

    def actions(self) -> list[Action]:
        return [a for _, a in self.steps if a is not None]

    def fingerprints(self) -> list[int]:
        return [fingerprint(s) for s, _ in self.steps]

    def encode(self) -> str:
        """Serialize as ``fp/fp/fp`` for Explorer URLs (path.rs:189-198)."""
        return "/".join(str(fp) for fp in self.fingerprints())

    @staticmethod
    def decode(encoded: str) -> list[int]:
        return [int(part) for part in encoded.split("/") if part]

    def __len__(self) -> int:
        return len(self.steps)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Path) and self.steps == other.steps

    def __hash__(self) -> int:
        return hash(self.encode())

    def __repr__(self) -> str:
        parts = []
        for state, action in self.steps:
            if action is not None:
                parts.append(f"{state!r} --{action!r}-->")
            else:
                parts.append(repr(state))
        return "Path(" + " ".join(parts) + ")"
