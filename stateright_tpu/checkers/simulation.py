"""Host simulation checker: random deep traces instead of exhaustive search.

Re-implements stateright src/checker/simulation.rs: a pluggable
``Chooser`` picks an init state and then one action per step
(simulation.rs:21-38); ``UniformChooser`` uses a seeded PRNG
(simulation.rs:50-78) with per-trace seeds derived from the base seed
(simulation.rs:114-167). Each trace runs from init until a terminal
state, a cycle (per-trace fingerprint set, simulation.rs:207, 250-261),
or the boundary. ``unique_state_count`` is approximate — it equals
``state_count`` (simulation.rs:380-384).

The TPU analog of this engine is N-parallel random walks under ``vmap``:
``CheckerBuilder.spawn_tpu_simulation`` (checkers/tpu_simulation.py).
"""

from __future__ import annotations

import random
import time
from typing import Optional, Protocol, Sequence

from ..checker import Checker, CheckerBuilder
from ..model import Expectation, Model, State
from ..fingerprint import fingerprint, stable_hash
from ..path import Path
from ..report import ReportData, Reporter


class Chooser(Protocol):
    """Picks init states and actions for one trace (simulation.rs:21-38)."""

    def new_trace(self, seed: int) -> "TraceChooser": ...


class TraceChooser(Protocol):
    def choose_init(self, init_states: Sequence[State]) -> State: ...

    def choose_action(self, model: Model, state: State, actions: Sequence) -> object: ...


class _UniformTrace:
    def __init__(self, seed: int):
        self._rng = random.Random(seed)

    def choose_init(self, init_states: Sequence[State]) -> State:
        return init_states[self._rng.randrange(len(init_states))]

    def choose_action(self, model: Model, state: State, actions: Sequence):
        return actions[self._rng.randrange(len(actions))]


class UniformChooser:
    """Uniform random choice with a stable seeded PRNG (simulation.rs:50-78)."""

    def new_trace(self, seed: int) -> _UniformTrace:
        return _UniformTrace(seed)


class SimulationChecker(Checker):
    def __init__(self, builder: CheckerBuilder, chooser: Chooser, seed: int):
        super().__init__(builder)
        self.chooser = chooser
        self.seed = seed

    def _run(self, reporter: Optional[Reporter] = None) -> None:
        model = self.model
        props = list(model.properties())
        ebits_init = self._eventually_bits_init()
        visitor = self.builder._visitor
        symmetry = self.builder._symmetry
        target_states = self.builder._target_state_count or 10_000
        target_depth = self.builder._target_max_depth

        init_states = [
            s for s in model.init_states() if model.within_boundary(s)
        ]
        if not init_states:
            return

        last_report = time.monotonic()
        trace_idx = 0
        while self._total_states < target_states and not self._all_discovered():
            # Per-trace seed: hash-combine of (base seed, trace index) so
            # distinct base seeds never share trace streams
            # (simulation.rs:114-167).
            trace = self.chooser.new_trace(stable_hash((self.seed, trace_idx)))
            trace_idx += 1
            state = trace.choose_init(init_states)
            steps: list[tuple[State, Optional[object]]] = []
            # Cycle detection via per-trace fingerprint set
            # (simulation.rs:207, 250-261); with symmetry enabled the
            # set holds representative digests (simulation.rs:252-256).
            seen: set[int] = set()
            ebits = ebits_init

            while True:
                fp = fingerprint(symmetry(state) if symmetry else state)
                if fp in seen:
                    # Cycle: end trace, not terminal (no eventually
                    # counterexample — same false negative as reference).
                    break
                seen.add(fp)
                self._total_states += 1
                self._max_depth = max(self._max_depth, len(seen))

                for i, prop in enumerate(props):
                    if prop.expectation == Expectation.ALWAYS:
                        if not prop.condition(model, state):
                            self._discover(prop.name, steps, state)
                    elif prop.expectation == Expectation.SOMETIMES:
                        if prop.condition(model, state):
                            self._discover(prop.name, steps, state)
                    else:
                        if ebits & (1 << i) and prop.condition(model, state):
                            ebits &= ~(1 << i)

                if self._all_discovered():
                    break
                if target_depth is not None and len(seen) >= target_depth:
                    break

                candidates = []
                for action in model.actions(state):
                    next_state = model.next_state(state, action)
                    if next_state is None:
                        continue
                    if not model.within_boundary(next_state):
                        continue
                    candidates.append((action, next_state))
                if not candidates:
                    # Terminal: surviving eventually bits are
                    # counterexamples.
                    if ebits:
                        for i, prop in enumerate(props):
                            if ebits & (1 << i):
                                self._discover(prop.name, steps, state)
                    break
                action = trace.choose_action(
                    model, state, [a for a, _ in candidates]
                )
                next_state = next(
                    s for a, s in candidates if a is action or a == action
                )
                steps.append((state, action))
                state = next_state

            if visitor is not None:
                visitor.visit(model, Path(steps + [(state, None)]))

            if reporter is not None:
                now = time.monotonic()
                if now - last_report >= reporter.delay():
                    last_report = now
                    reporter.report_checking(
                        ReportData(
                            total_states=self._total_states,
                            unique_states=self._total_states,
                            max_depth=self._max_depth,
                            duration_sec=self.duration_sec(),
                            done=False,
                        )
                    )
        # Approximate: unique == total (simulation.rs:380-384).
        self._unique_states = self._total_states

    def _discover(
        self, name: str, steps: list, final_state: State
    ) -> None:
        if name not in self._discoveries:
            from .. import telemetry

            prop = self.model.property_by_name(name)
            telemetry.emit(
                "verdict", property=name,
                expectation=prop.expectation.name.lower(),
                kind="discovery", wave=None, depth=len(steps),
            )
            self._discoveries[name] = Path(list(steps) + [(final_state, None)])
