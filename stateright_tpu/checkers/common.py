"""Shared engine internals for parent-pointer-based checkers.

The BFS and on-demand engines both maintain the child→parent
fingerprint forest of the reference's BFS (bfs.rs:28-29) and
reconstruct discovery paths by walking it (bfs.rs:371-400); the shared
code lives here so the engines cannot drift apart.
"""

from __future__ import annotations

from typing import Optional

from ..path import Path


def reduction_refusal(reduction: str, engine: str,
                      parts: list[str]) -> ValueError:
    """The shared formatter behind EVERY reduction refusal.

    Both the round-20 capability refusals (:func:`symmetry_refusal`)
    and the soundness-certificate refusals
    (:func:`soundness_refusal`) format through this one function, so
    serve-mode sessions and CLI runs print identical text: a
    ``"{reduction} reduction: {engine} cannot honor it"`` head
    followed by the caller's detail parts, joined by ``"; "``."""
    head = [f"{reduction} reduction: {engine} cannot honor it"]
    return ValueError("; ".join(head + list(parts)))


def symmetry_refusal(engine: str,
                     missing: Optional[str] = None) -> ValueError:
    """The ONE symmetry-refusal error every checker raises.

    Three engines used to hand-roll divergent messages (bfs,
    on_demand, tpu); this helper owns the wording so they cannot
    drift, and the device path's capability refusal (an encoding
    without a ``DeviceRewriteSpec``) names what is missing through
    the same channel. ``engine`` names the refusing spawn;
    ``missing`` names the absent capability, if the engine could
    otherwise honor the reduction."""
    parts = []
    if missing:
        parts.append(f"missing capability: {missing}")
    parts.append(
        "supported: spawn_dfs / spawn_simulation on the host (as in "
        "the reference: dfs.rs:300-311, simulation.rs:252-256), and "
        "the TPU sort-merge engines when the encoding declares "
        "device_rewrite_spec() (stateright_tpu/ops/canonical.py)"
    )
    return reduction_refusal("symmetry", engine, parts)


def soundness_refusal(engine: str, reduction: str, obligation: str,
                      detail: str) -> ValueError:
    """The certificate refusal: a declared spec/mask FAILED a
    soundness obligation (stateright_tpu/analysis/soundness.py), so
    the engine refuses to trust it.

    Unlike :func:`symmetry_refusal` (a capability gap), this names
    the exact obligation that could not be proven — the user's spec
    is the problem, not the engine. ``reduction`` is ``"symmetry"``
    or ``"ample-set"``; ``obligation`` is the analyzer rule name."""
    parts = [
        f"soundness certificate refused: obligation {obligation!r} "
        f"failed — {detail}",
        "pass --unsound-ok (CheckerBuilder.unsound_ok()) to run the "
        "uncertified reduction anyway "
        "(stateright_tpu/analysis/soundness.py)",
    ]
    return reduction_refusal(reduction, engine, parts)


class ParentTraceMixin:
    """Requires ``self.generated: dict[int, Optional[int]]``,
    ``self.model`` and ``self._discoveries``."""

    generated: dict[int, Optional[int]]

    def _reconstruct_fps(self, fp: int) -> list[int]:
        """Walk parent pointers back to an init state (bfs.rs:371-400)."""
        fps = [fp]
        while True:
            parent = self.generated[fps[-1]]
            if parent is None:
                break
            fps.append(parent)
        fps.reverse()
        return fps

    def _discover(self, name: str, fp: int,
                  depth: Optional[int] = None) -> None:
        if name not in self._discoveries:
            from .. import telemetry

            # The verdict lands BEFORE reconstruction (round 14):
            # time-to-verdict is when the search settled the
            # property, not when its path finished materializing —
            # the reconstruction wall has its own span below.
            prop = self.model.property_by_name(name)
            telemetry.emit(
                "verdict", property=name,
                expectation=prop.expectation.name.lower(),
                kind="discovery", wave=None, depth=depth,
            )
            with telemetry.span("counterexample_reconstruction",
                                property=name):
                self._discoveries[name] = Path.from_fingerprints(
                    self.model, self._reconstruct_fps(fp)
                )
