"""The TPU wave engine: breadth-first search as vectorized XLA programs.

This is the performance core of the framework — the re-design of the
reference's thread-pool BFS (src/checker/bfs.rs + src/job_market.rs)
for accelerators. One *wave* processes the entire frontier as a single
device program:

    frontier ──vmap step──▶ padded successors ──fingerprint──▶
    sort+unique ──▶ table insert-if-absent ──▶ compact new frontier

and the wave loop itself runs **on device** inside a jitted
``lax.while_loop``: the host synchronizes only once per chunk of waves
(default 64) or at termination, instead of once per wave. All search
state is device-resident between syncs:

* the visited table (open-addressing fingerprint set, ops/hashset.py),
* the parent forest — for every visited state, the fingerprint of the
  state that first generated it, stored in side arrays indexed by the
  state's table slot (the device equivalent of the reference's
  ``generated: DashMap<Fingerprint, Option<Fingerprint>>``,
  bfs.rs:28-29) — transferred to the host *once*, lazily, only when a
  counterexample path is reconstructed,
* per-property discovery flags and fingerprints,
* the frontier, its validity mask, and per-row ``EventuallyBits``
  (checker.rs:559-566, including the documented revisit
  false-negative, bfs.rs:285-303).

Property predicates are evaluated as bitmaps over the frontier each
wave. Path recovery replays the *host* model and matches device
fingerprints of encoded successors — which doubles as a continuous
differential check that the encoding agrees with the host semantics
(bfs.rs:371-400 + path.rs:20-97).

Multi-chip scale-out (sharded frontier + all-to-all shuffle by
fingerprint ownership, replacing job_market.rs work stealing) lives in
:mod:`stateright_tpu.parallel` and reuses this module's wave pieces
inside ``shard_map``.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Any, Optional

import numpy as np

from .. import faultinject
from ..checker import Checker, CheckerBuilder
from ..encoding import EncodedModel, has_trivial_boundary
from ..model import Expectation
from ..ops.fingerprint import fingerprint_u32v
from ..ops.hashset import DeviceHashSet, insert
from ..ops.u64 import U64, u64_add
from ..path import Path
from ..report import ReportData, Reporter
from .common import symmetry_refusal

_SENTINEL = 0xFFFFFFFF  # sort key for invalid successor rows

# Chunk programs are expensive to compile (the K-successor builder and
# probe loop unroll into a large XLA graph) and identical across
# checker instances with the same encoding, shapes and targets — cache.
_CHUNK_CACHE: dict = {}
_PERSISTENT_CACHE_SET = False


# -- compile-cache ledger (round 14, telemetry ``program_build``) ---------
#
# Where the multi-second cold compiles hide: jit compilation is LAZY,
# so the chunk program's XLA compile lands inside chunk 0's dispatch
# wall and the seed program's inside the seed_upload span — invisible
# as "compile" unless attributed. ``jax.monitoring`` observes every
# backend build-or-fetch in process: a BACKEND-compile duration fires
# once per ``compile_or_get_cached`` (a real cold compile OR a
# persistent-cache disk retrieval — the duration is the wall either
# way), and the cache_hits event fires exactly on a disk hit. Delta
# accounting around the engine's build/dispatch seams therefore gives
# EXACT per-program hit-tier attribution (in_process / disk / cold)
# with the measured cold wall — the warm/cold attribution the pending
# BENCH_r06 chip A/B reads off the artifact. Best effort: if the
# monitoring hooks are unavailable the tier degrades to "unknown" and
# nothing raises.
_COMPILE_MONITOR = {
    "installed": False,
    "compiles": 0,       # backend compile-or-fetch calls observed
    "compile_sec": 0.0,  # their total wall (cold compile or retrieval)
    "disk_hits": 0,      # persistent-cache disk hits among them
    "stage_sec": 0.0,    # jaxpr trace + MLIR lowering wall (the lazy
                         # jit work a fresh build pays BEFORE the
                         # backend compile — also part of the build)
}
_MONITOR_LOCK = threading.Lock()

_STAGE_EVENTS = (
    "/jax/core/compile/jaxpr_trace_duration",
    "/jax/core/compile/jaxpr_to_mlir_module_duration",
)


def _install_compile_monitor() -> None:
    if _COMPILE_MONITOR["installed"]:
        return
    try:
        from jax import monitoring

        def _on_event(event, **kw):
            if event == "/jax/compilation_cache/cache_hits":
                with _MONITOR_LOCK:
                    _COMPILE_MONITOR["disk_hits"] += 1

        def _on_duration(event, duration, **kw):
            if event == "/jax/core/compile/backend_compile_duration":
                with _MONITOR_LOCK:
                    _COMPILE_MONITOR["compiles"] += 1
                    _COMPILE_MONITOR["compile_sec"] += float(duration)
            elif event in _STAGE_EVENTS:
                with _MONITOR_LOCK:
                    _COMPILE_MONITOR["stage_sec"] += float(duration)

        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
        _COMPILE_MONITOR["installed"] = True
    except Exception:
        pass  # tier degrades to "unknown"; the ledger still lands


def _monitor_snapshot() -> tuple:
    with _MONITOR_LOCK:
        return (_COMPILE_MONITOR["compiles"],
                _COMPILE_MONITOR["compile_sec"],
                _COMPILE_MONITOR["disk_hits"],
                _COMPILE_MONITOR["stage_sec"])


def _monitor_delta(snap: tuple) -> tuple:
    now = _monitor_snapshot()
    return tuple(b - a for a, b in zip(snap, now))


def _resolve_tier(delta: tuple) -> tuple:
    """``(tier, wall_sec, cold_sec)`` from a monitor delta: what XLA
    actually did between the two snapshots. ``wall_sec`` is the FULL
    build wall as XLA measured it — jaxpr trace + lowering plus the
    backend compile-or-fetch — so subtracting it from a dispatch wall
    leaves the dispatch proper. The TIER keys on the backend half
    alone: trace/lower runs on every fresh jit call regardless of
    where the executable comes from. ``cold_sec`` is the real backend
    compile part (None when a mixed window can't split it)."""
    n, sec, hits, stage = delta
    if not _COMPILE_MONITOR["installed"]:
        return "unknown", 0.0, None
    if n == 0:
        return "in_process", stage, 0.0
    if hits == 0:
        return "cold", sec + stage, sec
    if hits >= n:
        return "disk", sec + stage, 0.0
    return "mixed", sec + stage, None


def compile_ledger_totals() -> dict:
    """Process-cumulative compile-cache counters (bench.py embeds the
    per-lane DELTA of this in each lane's detail and the run total in
    the provenance block, so a BENCH artifact carries its own
    warm/cold attribution)."""
    c, sec, hits, stage = _monitor_snapshot()
    return dict(
        installed=_COMPILE_MONITOR["installed"],
        compiles=c,
        disk_hits=hits,
        cold_compiles=c - hits,
        compile_sec=round(sec, 6),
        stage_sec=round(stage, 6),
    )


def _key_hash(cache_key) -> Optional[str]:
    """Short stable digest of a program cache key for the ledger
    (the full tuple holds types/classes; the digest is what two runs
    compare to see they fetched the SAME program)."""
    if cache_key is None:
        return None
    return hashlib.sha1(repr(cache_key).encode()).hexdigest()[:12]


def _enable_persistent_cache() -> None:
    """Route XLA compilations through a disk cache so repeated runs
    (tests, CLI re-invocations) skip the multi-second compile."""
    global _PERSISTENT_CACHE_SET
    _install_compile_monitor()
    if _PERSISTENT_CACHE_SET:
        return
    _PERSISTENT_CACHE_SET = True
    import os

    import jax

    if jax.config.jax_compilation_cache_dir is None:
        # Per-backend cache: under the axon tunnel, remote-compiled TPU
        # (and AOT CPU) artifacts target different machine features
        # than this host — sharing one directory across backends loads
        # incompatible executables (SIGILL risk).
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.expanduser(
                f"~/.cache/stateright_tpu_xla_{jax.default_backend()}"
            ),
        )
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def _fp_int(lo, hi) -> int:
    return (int(hi) << 32) | int(lo)


def _combine64(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    return (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)


def step_with_trunc(enc, rows, jnp):
    """vmap ``enc.step_vec`` over a row block, normalizing the optional
    truncation flag (see EncodedModel.step_vec) to a per-row bool:
    ``(succs[N,K,W], valid[N,K], trunc[N])``."""
    import jax

    res = jax.vmap(enc.step_vec)(rows)
    if len(res) == 3:
        return res
    succs, valid = res
    return succs, valid, jnp.zeros(rows.shape[0], dtype=bool)


def _props_and_ebits(cond_raw, F, fval, ebits, n_props, evt_idx, jnp):
    """The shared tail of both frontier_props variants: mask the
    property bitmap to live rows (bfs.rs:223-268) and clear satisfied
    eventually-bits (checker.rs:559-566) — one body so the row-major
    and transposed entry points cannot drift."""
    if n_props:
        cond = cond_raw & fval[:, None]
    else:
        cond = jnp.zeros((F, 0), dtype=bool)
    for i in evt_idx:
        ebits = jnp.where(cond[:, i], ebits & ~jnp.uint32(1 << i), ebits)
    return cond, ebits


def frontier_props(enc, props, evt_idx, frontier, fval, ebits,
                   sym_spec=None):
    """The step-free half of a wave: frontier fingerprints, the
    property bitmap, and eventually-bit clearing (shared between the
    dense expansion below and the sparse-dispatch path, which computes
    successors per enabled (row, slot) pair instead of per slot —
    extracting the pairs from the encoding's packed enabled-mask
    bitmap, ops/bitmask.py).

    ``sym_spec``: see :func:`frontier_props_t` — canonical
    fingerprints, concrete property evaluation.

    Returns ``(cond[F, P], ebits[F], f_lo[F], f_hi[F])``."""
    import jax
    import jax.numpy as jnp

    F = frontier.shape[0]
    n_props = len(props)
    fp_src = frontier
    if sym_spec is not None:
        from ..ops.canonical import canonicalize_rows

        fp_src = canonicalize_rows(sym_spec, frontier, jnp)
    f_lo, f_hi = fingerprint_u32v(fp_src, jnp)
    cond_raw = (
        jax.vmap(enc.property_conditions_vec)(frontier)
        if n_props else None
    )
    cond, ebits = _props_and_ebits(
        cond_raw, F, fval, ebits, n_props, evt_idx, jnp
    )
    return cond, ebits, f_lo, f_hi


def frontier_props_t(enc, props, evt_idx, frontier_t, fval, ebits,
                     sym_spec=None):
    """Transposed-resident variant of :func:`frontier_props`:
    ``frontier_t`` is the column-major ``uint32[W, F]`` block the
    sort-merge engines carry (PERF.md §layout). The fingerprint fold
    runs lane-major (``fingerprint_u32v_t`` — the measured 1.65x
    coalesced fold) and the property bitmap batches over axis 1, so
    no transpose of the resident buffer is ever materialized here;
    the mask/ebits tail is the SAME ``_props_and_ebits`` body.

    Returns ``(cond[F, P], ebits[F], f_lo[F], f_hi[F])`` — identical
    values to ``frontier_props(frontier_t.T, ...)``.

    With ``sym_spec`` set (device symmetry reduction), the returned
    fingerprints are CANONICAL — fingerprint(representative(state)) —
    while properties still evaluate on the concrete frontier
    (symmetric property sets give identical verdicts either way, and
    the concrete evaluation keeps counterexample states exact). The
    parent-log keys these fps seed must match the canonical child
    keys the dedup writes, which is why the canonicalization lives
    here and not only in the candidate pass."""
    import jax.numpy as jnp

    from ..encoding import property_conditions_cols
    from ..ops.fingerprint import fingerprint_u32v_t

    F = frontier_t.shape[1]
    n_props = len(props)
    fp_src = frontier_t
    if sym_spec is not None:
        from ..ops.canonical import canonicalize_t

        fp_src = canonicalize_t(sym_spec, frontier_t, jnp)
    f_lo, f_hi = fingerprint_u32v_t(fp_src, jnp)
    cond_raw = (
        property_conditions_cols(enc, frontier_t)
        if n_props else None
    )
    cond, ebits = _props_and_ebits(
        cond_raw, F, fval, ebits, n_props, evt_idx, jnp
    )
    return cond, ebits, f_lo, f_hi


def expand_frontier(enc, props, evt_idx, frontier, fval, ebits, expand,
                    with_repeats=True, sym_spec=None):
    """The shared first half of a wave (single-chip and sharded): from a
    frontier block to property verdicts + flattened candidate successors.

    Candidate fingerprints are deliberately NOT computed here — callers
    compact the valid candidates into a smaller buffer first and
    fingerprint only that (fingerprinting is per-lane splitmix64 in
    uint32 limb arithmetic, one of the wave's larger elementwise costs).

    Returns a dict with:
      ``cond``       bool[F, P]   property truth over valid frontier rows
      ``ebits``      uint32[F]    eventually-bits after clearing satisfied
      ``evt_cex``    bool[F]      terminal rows with surviving ebits
      ``f_lo/f_hi``  uint32[F]    frontier fingerprints
      ``flat``       uint32[F*K, W] candidate successors
      ``v``          bool[F*K]    candidate validity
      ``trunc``      bool[F]      rows whose encoding pruned an
                                  otherwise-valid successor at an
                                  internal bound (see EncodedModel.step_vec)
    and, only when ``with_repeats=True``:
      ``p_lo/p_hi``  uint32[F*K]  parent (frontier) fingerprints per candidate
      ``child_ebits`` uint32[F*K] ebits each candidate inherits
    (callers that index per-candidate data by ``row // K`` at the end of
    the wave — the adaptive sort-merge engine — pass False to skip
    materializing these F*K arrays)
    """
    import jax
    import jax.numpy as jnp

    F = frontier.shape[0]
    K, W = enc.max_actions, enc.width

    cond, ebits, f_lo, f_hi = frontier_props(
        enc, props, evt_idx, frontier, fval, ebits, sym_spec=sym_spec
    )

    succs, valid, trunc = step_with_trunc(enc, frontier, jnp)
    trunc = trunc & fval & expand
    valid = valid & fval[:, None] & expand
    # Trivial boundaries (no spec at all — EncodedModelBase's default,
    # or a compiled encoding's trivial_boundary flag) skip the [F, K]
    # predicate map entirely, mirroring the sparse wave's
    # sparse_boundary gate (tpu_sortmerge.py).
    if not has_trivial_boundary(enc):
        bound = jax.vmap(
            lambda row: jax.vmap(enc.within_boundary_vec)(row)
        )(succs)
        valid = valid & bound

    # Terminal rows: no successors at all → surviving eventually-bits
    # are counterexamples (bfs.rs:317-324). Depth-cut waves
    # (expand=False) are not terminal.
    terminal = fval & ~jnp.any(valid, axis=1) & expand
    evt_cex = terminal & (ebits != 0)

    out = dict(
        cond=cond,
        ebits=ebits,
        evt_cex=evt_cex,
        f_lo=f_lo,
        f_hi=f_hi,
        flat=succs.reshape(F * K, W),
        v=valid.reshape(F * K),
        trunc=trunc,
    )
    if with_repeats:
        out["p_lo"] = jnp.repeat(f_lo, K)
        out["p_hi"] = jnp.repeat(f_hi, K)
        out["child_ebits"] = jnp.repeat(ebits, K)
    return out


def wave_hits(props, ex, fval):
    """This wave's per-property discovery verdicts over the (local)
    frontier block: ``(hit[P] bool, lo[P], hi[P])`` — the fingerprint is
    of an arbitrary hitting row (the reference keeps whichever racing
    thread's discovery lands first, bfs.rs discovery recording)."""
    import jax.numpy as jnp

    cond, evt_cex, ebits = ex["cond"], ex["evt_cex"], ex["ebits"]
    f_lo, f_hi = ex["f_lo"], ex["f_hi"]
    hits, los, his = [], [], []
    for i, p in enumerate(props):
        if p.expectation == Expectation.ALWAYS:
            mask = fval & ~cond[:, i]
        elif p.expectation == Expectation.SOMETIMES:
            mask = cond[:, i]
        else:
            mask = evt_cex & ((ebits & jnp.uint32(1 << i)) != 0)
        hit = jnp.any(mask)
        row = jnp.argmax(mask)
        hits.append(hit)
        los.append(f_lo[row])
        his.append(f_hi[row])
    return jnp.stack(hits), jnp.stack(los), jnp.stack(his)


def discovery_update(props, ex, fval, disc_found, disc_lo, disc_hi):
    """Fold this wave's property verdicts into the carried per-property
    discovery flags/fingerprints, keeping the first (shallowest) hit —
    mirrors bfs.rs discovery recording."""
    import jax.numpy as jnp

    if not props:
        return disc_found, disc_lo, disc_hi
    hits, los, his = wave_hits(props, ex, fval)
    fresh = hits & ~disc_found
    return (
        disc_found | hits,
        jnp.where(fresh, los, disc_lo),
        jnp.where(fresh, his, disc_hi),
    )


class TpuBfsChecker(Checker):
    """``CheckerBuilder.spawn_tpu()`` — the reference's ``spawn_bfs``
    offloaded to a device (BASELINE.json north star)."""

    #: the hash engine keys its visited set on raw-state fingerprints
    #: with no canonicalization pass; the sort-merge subclasses flip
    #: this and honor symmetry via the encoding's DeviceRewriteSpec.
    _supports_device_symmetry = False
    _engine_name = "spawn_tpu (hash engine)"

    def __init__(
        self,
        builder: CheckerBuilder,
        encoded: Optional[EncodedModel] = None,
        capacity: int = 1 << 16,
        frontier_capacity: Optional[int] = None,
        track_paths: bool = True,
        waves_per_sync: int = 64,
        cand_capacity: Optional[int] = None,
        probe_rounds: int = 16,
        checkpoint_every: Optional[int] = None,
        checkpoint_path: Optional[str] = None,
    ):
        super().__init__(builder)
        if encoded is None:
            to_encoded = getattr(builder.model, "to_encoded", None)
            if to_encoded is None:
                raise ValueError(
                    "spawn_tpu requires an EncodedModel: pass encoded=... or "
                    "implement Model.to_encoded()"
                )
            encoded = to_encoded()
        self.encoded = encoded
        #: the device symmetry spec, when the reduction is ON for this
        #: run: the engines canonicalize candidates with it before the
        #: fingerprint fold (ops/canonical.py), so visited keys are
        #: canonical fingerprints while the frontier keeps concrete
        #: states. None = no reduction.
        self.sym_spec = None
        #: waive the soundness-certificate gates (--unsound-ok /
        #: CheckerBuilder.unsound_ok()): an UNCERTIFIED spec or mask
        #: runs anyway — research escape hatch, never the default.
        self.unsound_ok = bool(getattr(builder, "_unsound_ok", False))
        if builder._symmetry is not None:
            from ..encoding import device_rewrite_spec

            if not self._supports_device_symmetry:
                raise symmetry_refusal(self._engine_name)
            spec = device_rewrite_spec(encoded)
            if spec is None:
                raise symmetry_refusal(
                    self._engine_name,
                    missing=(
                        f"encoding {type(encoded).__name__} declares no "
                        "device_rewrite_spec() — the vectorized "
                        "canonicalization needs the strided bit-field "
                        "layout of the interchangeable limb group"
                    ),
                )
            # the certificate gate (analysis/soundness.py): a declared
            # spec only runs once its soundness obligations are
            # discharged — uncertifiable specs refuse here, at spawn,
            # with the failed obligation, unless explicitly waived.
            from ..analysis.soundness import gate_symmetry

            gate_symmetry(encoded, self._engine_name, self.unsound_ok)
            self.sym_spec = spec
        self.capacity = capacity
        #: summed across shards in sharded variants (occupancy metric).
        self.total_capacity = capacity
        self.frontier_capacity = frontier_capacity or capacity
        self.track_paths = track_paths
        self.waves_per_sync = waves_per_sync
        #: candidate-buffer rows per wave. The frontier is padded to F
        #: rows × K actions but most candidate rows are padding;
        #: compacting the valid ones into a smaller buffer before the
        #: sort/dedup/probe stages cuts the dominant per-wave costs.
        #: None = F*K (no compaction, can never overflow).
        self.cand_capacity = cand_capacity
        self.probe_rounds = probe_rounds
        if waves_per_sync < 1:
            raise ValueError(f"waves_per_sync must be >= 1: {waves_per_sync}")
        if probe_rounds < 1:
            raise ValueError(f"probe_rounds must be >= 1: {probe_rounds}")
        if cand_capacity is not None and cand_capacity < 1:
            raise ValueError(f"cand_capacity must be >= 1: {cand_capacity}")
        #: child vec-fingerprint -> parent vec-fingerprint (None = init);
        #: built lazily from the device-side parent forest (see
        #: _build_generated) only when a path is reconstructed.
        self.generated: Optional[dict[int, Optional[int]]] = None
        #: property name -> fingerprint of the discovery state; always
        #: populated (drives early exit) even when track_paths=False
        #: suppresses Path materialization.
        self._discovered_fps: dict[str, int] = {}
        self._programs = None  # (seed_fn, chunk_fn)
        self._final_tables: Optional[tuple] = None
        #: optional threading.Event: when set, _run returns after the
        #: current chunk with partial results and ``cancelled`` True
        #: (the hybrid racer's losing side; see checkers/hybrid.py).
        self.cancel_event = None
        self.cancelled = False
        #: optional context manager acquired around EVERY chunk
        #: dispatch+sync (both the untiered chunk loop and the tiered
        #: takeover loop funnel through ``_guarded_dispatch``): the
        #: resident service (stateright_tpu/serve.py) installs its
        #: FIFO device queue here so concurrent sessions interleave at
        #: chunk granularity without racing the device — one chunk in
        #: flight at a time, queued in arrival order. None (default) =
        #: no gate, zero overhead.
        self.dispatch_gate = None
        #: per-run wave metrics for observability (SURVEY §5): updated
        #: at each host sync point.
        self.metrics: dict[str, float] = {}
        #: the active RunTracer (telemetry.py), resolved at _run time;
        #: engine variants gate their device wave log on it.
        self._tracer = None
        #: per-ladder-class build info recorded by _build_programs
        #: (staging shapes, CHUNKED-mode records) — rides the program
        #: cache so cache-hit instances see it too (_lookup_programs).
        self._build_info = None
        #: the resident-buffer ledger (stateright_tpu/memplan.py),
        #: set by every _run — bench.py embeds its totals per lane
        #: even untraced; traced runs emit it as the memory_plan event.
        self.memory_plan = None
        #: round 14: whether the last ``_lookup_programs`` BUILT (vs
        #: fetched) — arms the compile-cache ledger's seed/chunk rows.
        self._fresh_build = False
        self._program_key_hash = None
        #: the untraced dispatch/sync wall split (``_run`` fills it;
        #: :meth:`latency_accounting` summarizes for bench.py).
        self._lat = None
        # -- checkpoint/resume (stateright_tpu/checkpoint.py) -----------
        #: snapshot the chunk carry every N chunks at the existing
        #: per-chunk sync (None = checkpointing off). The supervisor
        #: (checkpoint.supervised_run) then retries a failed chunk —
        #: device error, injected fault, OOM — from the last snapshot
        #: instead of dying.
        if checkpoint_every == "auto":
            # cadence picked from the measured snapshot write wall vs
            # chunk wall (checkpoint.auto_cadence, target <=5%
            # overhead); starts at every-chunk until both walls exist
            pass
        elif checkpoint_every is not None and checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1: {checkpoint_every}"
            )
        self.checkpoint_every = checkpoint_every
        self._ckpt_auto_every = 1
        self.checkpoint_path = checkpoint_path or (
            "stateright_tpu.ckpt" if checkpoint_every else None
        )
        #: bounded-retry budget of the fault supervisor, and the base
        #: of its exponential backoff (tests shrink it).
        self.max_fault_retries = 3
        self.retry_backoff_sec = 0.5
        # -- degrade-and-continue policy (checkpoint.FailurePolicy) ------
        #: allow the supervisor to drop a persistently-faulting shard
        #: and re-shard the last snapshot onto the survivors (sharded
        #: engines with checkpointing configured; CLI
        #: ``--degrade-on-fault``).
        self.degrade_on_fault = False
        #: shard-attributed failures before a fault classifies as
        #: persistent (checkpoint.FailurePolicy persist_threshold).
        self.fault_persist_threshold = 2
        # -- hung-dispatch watchdog (checkpoint.watchdog_deadline) -------
        #: None = off. Set (CLI ``--watchdog[=factor]``) to run every
        #: chunk dispatch+sync on a watchdog-supervised worker thread
        #: under a deadline of clamp(factor x rolling max chunk wall)
        #: — re-derived per chunk like the auto checkpoint cadence. A
        #: breach emits ``watchdog_timeout`` with the run's latency
        #: attribution and raises checkpoint.WatchdogTimeout (a
        #: supervised ``hang``).
        self.watchdog_factor = None
        self.watchdog_floor_sec = 2.0
        self.watchdog_cap_sec = 600.0
        #: the first-chunk grace (no measured chunk wall yet): the
        #: cold compile / persistent-cache disk fetch lands inside
        #: chunk 0's dispatch (a 17.9 s retrieval measured in
        #: TRACE_r21) and must never be misclassified as a hang.
        self.watchdog_grace_sec = 300.0
        #: rolling max chunk wall (NET of ledger-attributed build
        #: time) the deadline derives from; reset per spawn, kept
        #: across supervised retries (the walls are real either way).
        self._wd_roll_max = None
        # -- health layer (telemetry.detect_stragglers) ------------------
        #: None = off. On traced sharded runs, a shard whose per-wave
        #: work exceeds straggler_factor x the shard median emits a
        #: ``shard_health`` event (CLI ``--straggler-factor``);
        #: sustained stragglers feed the failure classifier as
        #: pre-fault evidence.
        self.straggler_factor = None
        #: consecutive straggler waves before a shard counts as a
        #: SUSTAINED straggler (classifier evidence).
        self.straggler_sustain = 3
        #: shard id -> consecutive straggler waves (live health state).
        self._shard_health: dict = {}
        #: staged (manifest, buffers) from :meth:`resume_from`; the
        #: next ``_run_attempt`` builds its carry from these instead
        #: of the seed program.
        self._resume = None
        self._resume_path = None
        self._last_snapshot = None
        #: sharded engines record their carry PartitionSpecs here at
        #: program build (rides the program cache) so a resume can
        #: place snapshot buffers with the exact mesh sharding.
        self._carry_pspecs = None

    # -- results ----------------------------------------------------------

    def discovered_property_names(self) -> set:
        """Names with a discovery — available even with
        ``track_paths=False`` (where full paths are not), and after a
        run that raised (e.g. an encoding-bound overflow in the same
        chunk that found the counterexample — the discovery, recorded
        before the raise, is the thing the check exists to surface).
        The error is suppressed only on REPLAY (the run already
        finished and raised once): a first call still surfaces it, so
        a caller that skipped ``join()`` can't mistake a truncated
        search for a clean one."""
        already_failed = self._done and self._run_error is not None
        try:
            self._ensure_run()
        except RuntimeError:
            if not (already_failed and self._discovered_fps):
                raise
        return set(self._discovered_fps)

    def discovery_fingerprints(self) -> dict[str, int]:
        """Property name -> discovery-state fingerprint. The fast-mode
        (track_paths=False) substitute for :meth:`discoveries`; like
        :meth:`discovered_property_names`, survives a raising run
        (replay only)."""
        already_failed = self._done and self._run_error is not None
        try:
            self._ensure_run()
        except RuntimeError:
            if not (already_failed and self._discovered_fps):
                raise
        return dict(self._discovered_fps)

    def discoveries(self):
        if not self.track_paths and self._discovered_fps:
            raise RuntimeError(
                "paths unavailable with track_paths=False; use "
                "discovered_property_names()/discovery_fingerprints(), or "
                "re-run with track_paths=True for counterexample traces"
            )
        return super().discoveries()

    def assert_properties(self) -> None:
        """Works in fast mode too: existence checks need only the
        discovery fingerprints, not materialized paths."""
        self._ensure_run()
        for prop in self.model.properties():
            has = prop.name in self._discovered_fps
            if prop.expectation == Expectation.SOMETIMES and not has:
                raise AssertionError(f"expected a discovery for {prop.name!r}")
            if prop.expectation != Expectation.SOMETIMES and has:
                raise AssertionError(
                    f"unexpected discovery for {prop.name!r}: "
                    f"{self._discovered_fps[prop.name]:#018x}"
                )

    # -- device program ----------------------------------------------------
    #
    # The axon-tunneled TPU makes every host<->device transfer cost
    # hundreds of milliseconds regardless of size (latency, not
    # bandwidth). The whole run therefore touches the host exactly:
    #   1 upload   — the deduped init states (seed_fn builds the rest
    #                of the carry on device),
    #   1 dispatch + 1 small packed-stats readback per chunk of
    #                ``waves_per_sync`` waves,
    #   0 downloads of the tables unless a counterexample path is
    #                actually reconstructed (lazy, _build_generated).

    def _build_programs(self, n0: int):
        import jax
        import jax.numpy as jnp
        from jax import lax

        enc = self.encoded
        props = list(self.model.properties())
        n_props = len(props)
        evt_idx = [
            i for i, p in enumerate(props)
            if p.expectation == Expectation.EVENTUALLY
        ]
        if evt_idx and max(evt_idx) >= 32:
            # ebits ride in a uint32 lane; 1 << 32 would silently wrap.
            raise ValueError(
                "the TPU engine supports eventually properties only at "
                "property indices < 32; reorder properties() so eventually "
                f"properties come first (got index {max(evt_idx)})"
            )
        K, W, F = enc.max_actions, enc.width, self.frontier_capacity
        capacity = self.capacity
        B = min(self.cand_capacity or F * K, F * K)
        probe_rounds = self.probe_rounds
        target_states = self.builder._target_state_count
        target_depth = self.builder._target_max_depth
        waves_per_sync = self.waves_per_sync
        ebits_init = self._eventually_bits_init()
        track_paths = self.track_paths
        # Candidate payload lanes: state + (parent fp if tracked) + ebits.
        E = W + 3 if track_paths else W + 1
        EB = E - 1  # ebits lane index

        def seed(init_rows):
            """Build the entire device carry from the init states."""
            frontier = jnp.zeros((F, W), dtype=jnp.uint32).at[:n0].set(
                init_rows
            )
            fval = jnp.arange(F) < n0
            ebits = jnp.where(fval, jnp.uint32(ebits_init), jnp.uint32(0))
            lo0, hi0 = fingerprint_u32v(init_rows, jnp)
            table = DeviceHashSet.empty(capacity, jnp)
            table, _, pending, _ = insert(
                table, lo0, hi0, jnp.ones(n0, dtype=bool), jnp,
                rounds=probe_rounds,
            )
            out = dict(
                t_lo=table.lo,
                t_hi=table.hi,
                # Parent 0 means "init/root": fingerprints are never 0.
                # Untracked runs carry empty side tables (no per-wave
                # parent scatters, no memory).
                p_lo_t=jnp.zeros(capacity if track_paths else 0, jnp.uint32),
                p_hi_t=jnp.zeros(capacity if track_paths else 0, jnp.uint32),
                frontier=frontier,
                fval=fval,
                ebits=ebits,
                depth=jnp.int32(1),
                wchunk=jnp.int32(0),
                waves=jnp.uint32(0),
                gen_lo=jnp.uint32(n0),
                gen_hi=jnp.uint32(0),
                new=jnp.uint32(n0),
                disc_found=jnp.zeros(n_props, dtype=bool),
                disc_lo=jnp.zeros(n_props, dtype=jnp.uint32),
                disc_hi=jnp.zeros(n_props, dtype=jnp.uint32),
                overflow=jnp.any(pending),
                f_overflow=jnp.bool_(False),
                c_overflow=jnp.bool_(False),
                e_overflow=jnp.bool_(False),
                done=jnp.bool_(n0 == 0) | jnp.any(pending),
            )
            # engine-variant carry extension (the fused multi-session
            # engine adds per-session lanes — stateright_tpu/batch.py);
            # base: no extra keys, identical program
            out.update(self._seed_extra(out, init_rows, jnp))
            return out

        def body(c):
            table = DeviceHashSet(c["t_lo"], c["t_hi"])
            ebits = c["ebits"]
            fval = c["fval"]

            if target_depth is None:
                expand = jnp.bool_(True)
            else:
                # States at the depth cut are evaluated, not expanded
                # (bfs.rs:210-215 semantics).
                expand = c["depth"] < target_depth

            ex = expand_frontier(
                enc, props, evt_idx, c["frontier"], fval, ebits, expand
            )
            e_overflow = c["e_overflow"] | jnp.any(ex["trunc"])

            disc_found, disc_lo, disc_hi = discovery_update(
                props, ex, fval, c["disc_found"], c["disc_lo"], c["disc_hi"]
            )

            n_cand = jnp.sum(ex["v"])
            # Candidate payload: state lanes (+ parent fp when paths are
            # tracked) + ebits packed into one [*, E] array so
            # compaction/reorder is one scatter/gather instead of five.
            parts = [ex["flat"]]
            if track_paths:
                parts += [ex["p_lo"][:, None], ex["p_hi"][:, None]]
            parts.append(ex["child_ebits"][:, None])
            ext = jnp.concatenate(parts, axis=1)
            if B < F * K:
                # Compact the valid candidates into a B-row buffer:
                # typically only a small fraction of the F*K padded
                # candidate rows is valid, and every downstream op
                # (fingerprint, sort, probe, scatter) then runs on B
                # rows.
                cpos = jnp.cumsum(ex["v"]) - 1
                csp = jnp.where(ex["v"], cpos, B)
                b_ext = jnp.zeros((B, E), jnp.uint32).at[csp].set(
                    ext, mode="drop"
                )
                b_val = jnp.arange(B) < n_cand
                c_overflow = c["c_overflow"] | (n_cand > B)
            else:
                b_ext = ext
                b_val = ex["v"]
                c_overflow = c["c_overflow"]
            b_lo, b_hi = fingerprint_u32v(b_ext[:, :W], jnp)

            # Insert-if-absent; duplicate candidates within the wave
            # resolve inside the probe loop (one winner per key), so no
            # sort-unique pass is needed.
            table, is_new, pending, slots = insert(
                table, b_lo, b_hi, b_val, jnp, rounds=probe_rounds
            )
            overflow = c["overflow"] | jnp.any(pending)
            s_ext = b_ext

            if track_paths:
                # Parent forest: write each new state's parent
                # fingerprint at its table slot (device-resident
                # bfs.rs:28-29).
                par_idx = jnp.where(is_new, slots, jnp.uint32(capacity))
                p_lo_t = c["p_lo_t"].at[par_idx].set(
                    s_ext[:, W], mode="drop"
                )
                p_hi_t = c["p_hi_t"].at[par_idx].set(
                    s_ext[:, W + 1], mode="drop"
                )
            else:
                p_lo_t, p_hi_t = c["p_lo_t"], c["p_hi_t"]

            # Compact new states into the next frontier. Non-new rows
            # scatter to index F, out of range for every [F]-sized
            # output buffer — dropped.
            new_count = jnp.sum(is_new)
            pos = jnp.cumsum(is_new) - 1
            scatter_pos = jnp.where(is_new, pos, F)
            next_fe = jnp.zeros((F, E), dtype=jnp.uint32).at[
                scatter_pos
            ].set(s_ext, mode="drop")
            next_frontier = next_fe[:, :W]
            next_ebits = next_fe[:, EB]
            next_fval = jnp.arange(F) < new_count
            f_overflow = c["f_overflow"] | (new_count > F)

            g = u64_add(
                U64(c["gen_lo"], c["gen_hi"]),
                U64(n_cand.astype(jnp.uint32), jnp.uint32(0)),
            )
            new = c["new"] + new_count.astype(jnp.uint32)

            all_disc = (
                jnp.all(disc_found) if n_props else jnp.bool_(False)
            )
            if target_states is None:
                target_hit = jnp.bool_(False)
            else:
                target_hit = new >= jnp.uint32(target_states)
            cont = (
                (new_count > 0)
                & ~all_disc
                & ~target_hit
                & ~overflow
                & ~f_overflow
                & ~c_overflow
                & ~e_overflow
            )
            out = dict(
                t_lo=table.lo,
                t_hi=table.hi,
                p_lo_t=p_lo_t,
                p_hi_t=p_hi_t,
                frontier=next_frontier,
                fval=next_fval & cont,
                ebits=next_ebits,
                depth=jnp.where(cont, c["depth"] + 1, c["depth"]),
                wchunk=c["wchunk"] + 1,
                waves=c["waves"] + 1,
                gen_lo=g.lo,
                gen_hi=g.hi,
                new=new,
                disc_found=disc_found,
                disc_lo=disc_lo,
                disc_hi=disc_hi,
                overflow=overflow,
                f_overflow=f_overflow,
                c_overflow=c_overflow,
                e_overflow=e_overflow,
                done=~cont,
            )
            # Engine-variant wave extension: the hook sees the wave's
            # internals (candidates, winners) and must return EVERY
            # extra carry key it seeded (while_loop carries have a
            # fixed structure); it may also override base keys (the
            # fused engine masks fval by per-session settlement).
            out.update(self._body_extra(
                c, out,
                dict(ex=ex, b_ext=b_ext, b_val=b_val, is_new=is_new,
                     new_count=new_count, n_cand=n_cand),
                jnp,
            ))
            return out

        def cond(c):
            return ~c["done"] & (c["wchunk"] < waves_per_sync)

        # Memory ledger (memplan.py): the hash engine has no ladder —
        # one fixed-shape class whose staging is the flat candidate
        # payload, its compacted B-row buffer, and the key limbs.
        from ..memplan import buffer_entry, plan_total

        _staging = [
            buffer_entry("cand_payload", (F * K, E), "uint32"),
            buffer_entry("cand_compact", (B, E), "uint32"),
            buffer_entry("cand_keys", (2, B), "uint32"),
        ]
        self._build_info = dict(
            classes=[dict(
                f_class=0, v_class=0, mode="hash",
                frontier_rows=F, visited_rows=capacity,
                staging=_staging, staging_bytes=plan_total(_staging),
            )],
            v_classes=[],
            engine_modes=[],
        )

        def chunk(carry):
            c = dict(carry, wchunk=jnp.int32(0))
            c = lax.while_loop(cond, body, c)
            # Everything the host polls, packed into ONE small array so
            # each chunk costs exactly one readback over the tunnel.
            scalars = jnp.stack(
                [
                    c["done"].astype(jnp.uint32),
                    c["overflow"].astype(jnp.uint32),
                    c["f_overflow"].astype(jnp.uint32),
                    c["depth"].astype(jnp.uint32),
                    c["waves"],
                    jnp.sum(c["fval"]).astype(jnp.uint32),
                    c["gen_lo"],
                    c["gen_hi"],
                    c["new"],
                    c["c_overflow"].astype(jnp.uint32),
                    c["e_overflow"].astype(jnp.uint32),
                ]
            )
            stats = jnp.concatenate(
                [
                    scalars,
                    c["disc_found"].astype(jnp.uint32),
                    c["disc_lo"],
                    c["disc_hi"],
                ]
                # engine-variant stat lanes AFTER the discovery lanes
                # (consumed host-side by _consume_extra_stats /
                # _on_chunk_stats); base: none
                + list(self._stats_extra(c, jnp))
            )
            return c, stats

        return jax.jit(seed), jax.jit(chunk, donate_argnums=0)

    # -- host orchestration ------------------------------------------------

    def _run(self, reporter: Optional[Reporter] = None) -> None:
        """One engine run, supervised (stateright_tpu/checkpoint.py):
        with checkpointing or a staged resume configured, a failed
        chunk retries from the last snapshot with bounded backoff;
        otherwise this is a plain pass-through to ``_run_attempt``."""
        from .. import checkpoint

        checkpoint.supervised_run(self, reporter)

    def resume_from(self, path: str, **kw) -> dict:
        """Stage a snapshot (checkpoint.resume_from) so the next run
        restores the chunk carry instead of seeding — on the SAME
        layout by direct upload, on a different sort-merge shard
        count/capacity through the (owner, fp) re-route. Returns the
        snapshot manifest; raises the named Snapshot* errors on
        corruption/staleness/incompatibility."""
        from .. import checkpoint

        return checkpoint.resume_from(self, path, **kw)

    def _checkpoint_family(self) -> str:
        """Snapshot-compatibility family: engines whose visited
        structures are interconvertible under the (owner, fp)
        re-route share a family (the sort-merge engines override)."""
        return "hash"

    def _reset_for_resume(self) -> None:
        """Discard one failed attempt's partial results before the
        supervisor retries from a snapshot. Programs are KEPT (the
        fault was at runtime, not in the compiled shapes; an OOM
        degrade clears them itself); discoveries re-derive from the
        snapshot's cumulative discovery lanes."""
        self._discovered_fps.clear()
        self._discoveries.clear()
        self._total_states = 0
        self._unique_states = 0
        self._max_depth = 0
        self.metrics = {}
        self.generated = None
        self._final_tables = None
        # the fresh attempt starts with clean health state (the
        # classifier already consumed the failed attempt's evidence)
        self._shard_health = {}

    def _degrade_memory_lean(self) -> bool:
        """Supervisor hook after repeated OOMs: shrink towards a
        memory-lean configuration before the next retry. The base
        hash engine has no lean mode (False = nothing degraded); the
        sort-merge engines shrink ``flat_budget_bytes``, flipping
        their big classes into CHUNKED mode."""
        return False

    def _pre_run_check(self) -> None:
        """Hook: configuration validation that must land BEFORE any
        program build or device work. Base engine: nothing to check
        (the sort-merge engines pre-check the tiered
        frontier-headroom bound here)."""

    # -- degrade-and-continue (checkpoint.FailurePolicy) -------------------

    def _fault_shards(self):
        """The live shard-id set the fault-injection hook filters
        persistent ``shard_fault`` faults against (None = single-chip
        / unfiltered). The sharded engines set ``_shard_ids`` at mesh
        construction; a degrade removes the dropped shard, which is
        exactly what makes the injected dead chip stop firing."""
        return getattr(self, "_shard_ids", None)

    def _can_degrade_shards(self) -> bool:
        """Whether the supervisor may drop a shard from this run: a
        mesh engine with more than one shard left. Both families
        qualify — the sort-merge re-shard and the sharded-hash
        re-insertion route both carry a snapshot to the new count."""
        return (getattr(self, "mesh", None) is not None
                and int(getattr(self, "n_shards", 1)) > 1)

    def _degrade_shards(self, exclude_shard=None) -> None:
        """Drop one shard from the mesh (the supervisor's elastic
        degrade): rebuild the Mesh over the surviving devices and
        invalidate everything keyed on the old layout — programs,
        memory plan, carry PartitionSpecs. The next resume routes the
        snapshot through the (owner, fp) re-shard because the layouts
        now differ; counts are bit-exact by the PR 11 proof."""
        from jax.sharding import Mesh

        if not self._can_degrade_shards():
            raise RuntimeError(
                "shard degrade needs a mesh engine with > 1 shard"
            )
        devices = list(self.mesh.devices.reshape(-1))
        ids = list(getattr(self, "_shard_ids",
                           range(self.n_shards)))
        if exclude_shard in ids:
            keep = [(d, i) for d, i in zip(devices, ids)
                    if i != exclude_shard]
        else:
            # no attributed shard: shed the last one (capacity loss
            # is the same; the classifier had no better signal)
            keep = list(zip(devices, ids))[:-1]
        self.mesh = Mesh(
            np.array([d for d, _ in keep]), ("shard",)
        )
        self.n_shards = len(keep)
        self._shard_ids = tuple(i for _, i in keep)
        self.total_capacity = self.capacity * self.n_shards
        self._programs = None
        self.memory_plan = None
        self._carry_pspecs = None
        self._shard_health = {}

    # -- health layer (telemetry.detect_stragglers) ------------------------

    def _sustained_stragglers(self) -> tuple:
        """Shards the health layer currently holds as SUSTAINED
        stragglers (consecutive straggler waves >= straggler_sustain)
        — the pre-fault evidence checkpoint.classify_failure uses to
        attribute an otherwise-anonymous transient fault. Reported in
        ORIGINAL shard-id space (``_shard_ids``)."""
        return tuple(
            s for s, n in sorted(self._shard_health.items())
            if n >= self.straggler_sustain
        )

    def _note_shard_health(self, srows, wave0: int) -> None:
        """Feed one chunk's per-shard wave-log rows through the
        straggler detector (telemetry.detect_stragglers): per wave, a
        shard whose work exceeds ``straggler_factor`` x the shard
        median emits a schema-validated ``shard_health`` event and
        advances its consecutive-straggler count; a clean wave resets
        it. No-op unless sharded + traced + straggler_factor set."""
        factor = self.straggler_factor
        if not factor or srows is None:
            return
        from .. import telemetry

        ids = getattr(self, "_shard_ids", None) or tuple(
            range(srows.shape[0])
        )
        n_waves = srows.shape[1]
        for w in range(n_waves):
            flagged = telemetry.detect_stragglers(
                srows[:, w, :], factor
            )
            hit = {rec["shard"] for rec in flagged}
            for pos in range(srows.shape[0]):
                sid = ids[pos] if pos < len(ids) else pos
                if pos in hit:
                    self._shard_health[sid] = (
                        self._shard_health.get(sid, 0) + 1
                    )
                else:
                    self._shard_health[sid] = 0
            for rec in flagged:
                sid = (ids[rec["shard"]]
                       if rec["shard"] < len(ids) else rec["shard"])
                telemetry.emit(
                    "shard_health",
                    kind="straggler",
                    shard=int(sid),
                    wave=int(wave0 + w),
                    factor=float(factor),
                    value=int(rec["value"]),
                    median=float(rec["median"]),
                    ratio=round(float(rec["ratio"]), 4),
                    sustained=int(self._shard_health.get(sid, 0)),
                )

    # -- hung-dispatch watchdog (checkpoint.watchdog_deadline) -------------

    def _guarded_dispatch(self, thunk, chunk_no: int):
        """Run one chunk's dispatch+sync, under the watchdog when
        configured: the thunk executes on a daemon worker thread and
        the host waits at most the derived deadline. A breach emits
        ``watchdog_timeout`` with the run's full latency attribution
        and raises checkpoint.WatchdogTimeout — the supervisor's
        ``hang`` class. The hung thread is abandoned (XLA offers no
        cancellation); an injected hang's sleeper finishes harmlessly,
        a genuinely wedged runtime exhausts the retry budget and the
        WatchdogTimeout raises through with the diagnosis.

        When a ``dispatch_gate`` is installed (the resident service's
        FIFO device queue, stateright_tpu/serve.py), the whole
        dispatch+sync — watchdog-supervised or plain — runs inside it:
        this method is the ONE seam both chunk loops (untiered and
        tiered takeover) pass through, so gating here is what makes
        concurrent sessions interleave at chunk granularity."""
        gate = getattr(self, "dispatch_gate", None)
        if gate is not None:
            with gate:
                return self._dispatch_supervised(thunk, chunk_no)
        return self._dispatch_supervised(thunk, chunk_no)

    def _dispatch_supervised(self, thunk, chunk_no: int):
        if not getattr(self, "watchdog_factor", None):
            return thunk()
        from .. import checkpoint as _ckpt
        from .. import telemetry

        deadline = _ckpt.watchdog_deadline(
            self._wd_roll_max, self.watchdog_factor,
            floor_sec=self.watchdog_floor_sec,
            cap_sec=self.watchdog_cap_sec,
            first_grace_sec=self.watchdog_grace_sec,
        )
        box: dict = {}
        done = threading.Event()

        def run():
            try:
                box["out"] = thunk()
            except BaseException as exc:  # re-raised on the host side
                box["exc"] = exc
            finally:
                done.set()

        t0 = time.monotonic()
        worker = threading.Thread(
            target=run, daemon=True,
            name=f"stpu-watchdog-chunk{chunk_no}",
        )
        worker.start()
        if not done.wait(deadline):
            att = dict(
                chunk=int(chunk_no),
                deadline_sec=round(deadline, 3),
                rolling_max_chunk_sec=(
                    None if self._wd_roll_max is None
                    else round(self._wd_roll_max, 6)
                ),
                factor=float(self.watchdog_factor),
                waited_sec=round(time.monotonic() - t0, 3),
                latency=self.latency_accounting(),
            )
            telemetry.emit("watchdog_timeout", **att)
            raise _ckpt.WatchdogTimeout(chunk_no, deadline, att)
        if "exc" in box:
            raise box["exc"]
        return box["out"]

    def _note_watchdog_wall(self, wall_sec: float,
                            wd_snap) -> None:
        """Feed one completed chunk's wall into the watchdog's
        rolling max, NET of ledger-attributed build time (the monitor
        delta across the chunk) so a one-off cold compile or disk
        fetch never inflates — or, worse, becomes — the hang
        baseline."""
        if not getattr(self, "watchdog_factor", None):
            return
        net = wall_sec
        if wd_snap is not None:
            _, build_sec, _, stage_sec = _monitor_delta(wd_snap)
            net = max(wall_sec - build_sec - stage_sec, 0.0)
        if self._wd_roll_max is None or net > self._wd_roll_max:
            self._wd_roll_max = net

    def _run_attempt(self, reporter: Optional[Reporter] = None) -> None:
        import jax.numpy as jnp

        from .. import telemetry

        # Telemetry attach (stateright_tpu/telemetry.py): resolved
        # ONCE per run, BEFORE program build — engine variants gate
        # their device-side wave log (and its cache key) on it. At
        # level="deep" the engine takes the extra syncs the default
        # path refuses: one wave per chunk, so every wave gets a real
        # wall time and a device/fetch split (counts are invariant to
        # waves_per_sync — it only sets the sync cadence).
        tracer = telemetry.current_tracer()
        self._tracer = tracer
        if (tracer is not None and tracer.level == "deep"
                and self.waves_per_sync != 1):
            tracer.event(
                "deep_sync_override",
                waves_per_sync_old=self.waves_per_sync,
                waves_per_sync=1,
            )
            self.waves_per_sync = 1
            self._programs = None
            # the wave-log shape changed with waves_per_sync: the
            # ledger must re-derive from the rebuilt programs
            self.memory_plan = None

        enc = self.encoded
        props = list(self.model.properties())
        n_props = len(props)
        F, W = self.frontier_capacity, enc.width
        if self.builder._visitor is not None:
            raise ValueError(
                "visitors require a host checker (spawn_bfs/spawn_dfs); the "
                "TPU engine keeps full states on device only"
            )

        # Dedup init states host-side (they are few) so the device seed
        # can assume distinct rows.
        init = np.asarray(enc.init_vecs(), dtype=np.uint32).reshape(-1, W)
        seen = set()
        rows = []
        for row in init:
            fp = self._vec_fp(row)
            if fp not in seen:
                seen.add(fp)
                rows.append(row)
        init = np.stack(rows) if rows else np.zeros((0, W), np.uint32)
        n0 = init.shape[0]
        if n0 > F:
            raise ValueError(f"frontier capacity {F} < {n0} init states")

        # A racer (checkers/hybrid.py) may have already won before the
        # device program is even built — skip the (potentially
        # multi-second) trace/compile entirely. A win landing DURING
        # the build still blocks until the build returns; the chunk
        # loop below re-checks per chunk.
        if self.cancel_event is not None and self.cancel_event.is_set():
            self.cancelled = True
            return
        # Dispatch/sync-floor accounting (round 14): the host wall
        # split kept even UNTRACED (a handful of float adds per chunk
        # — bench.py embeds it per lane), reset per attempt so an
        # auto-budget retry reports its final attempt.
        self._lat = dict(
            chunks=0, dispatch_sec=0.0, fetch_sec=0.0,
            device_sec=0.0, fetch_min=None,
            t_start=time.monotonic(), t_first_sync=None,
        )
        # config pre-checks that must land BEFORE any program build
        # or device work (e.g. the tiered frontier-headroom bound —
        # the sort-merge engines override)
        self._pre_run_check()
        if self._programs is None:
            with telemetry.span("compile", engine=type(self).__name__):
                self._programs = self._lookup_programs(n0)
        seed_fn, chunk_fn = self._programs

        # Resident-buffer ledger (stateright_tpu/memplan.py): the
        # plan is declared at program-build time from the seed
        # program's OWN output spec (jax.eval_shape — no allocation,
        # no compile), so the declaration cannot drift from the
        # carry the engine actually keeps resident. Always kept on
        # the checker (bench.py embeds the totals untraced); emitted
        # as the schema-validated ``memory_plan`` event — with the
        # compiled-program memory analysis attached, the one part
        # that costs an AOT compile the persistent XLA cache dedups
        # — only when a tracer is active. Computed once per BUILT
        # program, not per run: re-joins of one checker reuse it
        # (the untraced overhead pool must not pay a per-run seed
        # retrace), and every site that rebuilds programs (retry
        # resize, deep-sync override) clears it alongside.
        plan_key = (n0, self._wave_log_enabled())
        if (self.memory_plan is None
                or getattr(self, "_memory_plan_key", None)
                != plan_key):
            self.memory_plan = self._memory_plan(
                n0, with_compiled=tracer is not None
            )
            self._memory_plan_key = plan_key
        if tracer is not None:
            for mode in (getattr(self, "_build_info", None)
                         or {}).get("engine_modes", ()):
                tracer.event("engine_mode", **mode)
            tracer.event("memory_plan", **self.memory_plan)

        # Fresh builds pay their XLA compiles lazily: the seed
        # program's inside this span, the chunk program's inside
        # chunk 0's dispatch — bracket both with monitor snapshots so
        # each lands as its own compile-cache ledger row with the
        # measured tier and cold wall (telemetry ``program_build``).
        ledger_pending = (tracer is not None
                          and getattr(self, "_fresh_build", False))
        resume = self._resume
        prev_waves = 0
        if resume is not None:
            # Restore (stateright_tpu/checkpoint.py): the staged
            # snapshot buffers become the initial carry — the seed
            # program never runs, the chunk loop continues from the
            # snapshot's wave. Consumed here so a later fault's
            # supervisor retry re-stages from disk explicitly.
            from .. import checkpoint as _ckpt

            self._resume = None
            manifest, buffers = resume
            import jax as _jax

            spec = _jax.eval_shape(
                seed_fn,
                _jax.ShapeDtypeStruct((n0, W), jnp.uint32),
            )
            with telemetry.span("restore_upload"):
                carry = _ckpt.build_resume_carry(
                    self, manifest, buffers, spec
                )
            prev_waves = int(manifest["wave"])
            if tracer is not None:
                tracer.event(
                    "restore",
                    path=os.path.basename(
                        self._resume_path or ""
                    ) or None,
                    wave=int(manifest["wave"]),
                    depth=int(manifest["depth"]),
                    unique=int(manifest["unique"]),
                    from_shards=int(manifest.get("n_shards", 1)),
                    to_shards=int(getattr(self, "n_shards", 1)),
                )
        else:
            snap = _monitor_snapshot() if ledger_pending else None
            with telemetry.span("seed_upload"):
                carry = seed_fn(jnp.asarray(init))  # the one upload
            if ledger_pending:
                self._emit_program_build("seed", snap)

        chunk_idx = 0
        verdicts_seen: set = set()
        deep = tracer is not None and tracer.level == "deep"
        # Live watermarks: device bytes-in-use polled ONLY at the
        # existing per-chunk sync (the stats readback just blocked —
        # no new syncs), traced runs only so the untraced host path
        # is untouched.
        mem_peak = None
        mem_src = None
        mem_polls = 0
        # chunks executed THIS attempt (checkpoint cadence + the
        # fault-injection sites key on it; restarts at 0 on a resumed
        # attempt so an armed once-only fault can't re-trip itself)
        chunk_no = 0
        self._tier_mem = None
        while True:
            if self.cancel_event is not None and self.cancel_event.is_set():
                self.cancelled = True
                return
            # Tiered visited set (stateright_tpu/tier.py): once the
            # hot ceiling is crossed (or a resumed snapshot carries
            # cold runs), the sort-merge engines take the run over —
            # spill at this sync, then the deferred-commit tiered
            # chunk loop to completion. No-op (None) on every other
            # engine and below the ceiling.
            took = self._tier_takeover(carry, n0, chunk_no, reporter)
            if took is not None:
                carry, s = took
                if self.cancelled:
                    return
                break
            t0 = time.monotonic()
            chunk_snap = _monitor_snapshot() if ledger_pending else None
            wd_snap = (_monitor_snapshot()
                       if getattr(self, "watchdog_factor", None)
                       else None)

            def exec_chunk(carry=carry, chunk_no=chunk_no):
                # fault-injection seam: a device error surfacing
                # from the mesh collective path (mesh engines only;
                # no-op with nothing armed)
                if getattr(self, "mesh", None) is not None:
                    faultinject.fire("collective_seam", chunk_no,
                                     shards=self._fault_shards())
                # Sharded engines return a third output when traced:
                # the per-shard mesh wave log
                # (telemetry.SHARD_LOG_FIELDS), sharded across
                # devices — it rides the same dispatch and the same
                # sync point as the packed stats.
                out = chunk_fn(carry)
                c_out, stats = out[0], out[1]
                slog = out[2] if len(out) > 2 else None
                # fault-injection seam: a device error surfacing
                # between the async dispatch and the stats readback
                # (no-op with nothing armed — faultinject.py)
                faultinject.fire("mid_chunk", chunk_no,
                                 shards=self._fault_shards())
                td = time.monotonic()  # async dispatch returns here
                t_dv = td
                dsec = None
                if deep:
                    # The deep level's extra sync: block on the carry
                    # so the device compute and the stats fetch split
                    # apart.
                    import jax

                    jax.block_until_ready(c_out)
                    t_dv = time.monotonic()
                    dsec = t_dv - td
                s_np = np.asarray(stats)  # the chunk's one readback
                return c_out, s_np, slog, td, t_dv, dsec

            # the whole dispatch+sync runs under the hung-dispatch
            # watchdog when configured (worker thread + derived
            # deadline); a plain inline call otherwise
            carry, s, shard_log, t_disp, t_dev, dev_sec = \
                self._guarded_dispatch(exec_chunk, chunk_no)
            t1 = time.monotonic()
            if chunk_snap is not None:
                # the chunk program's compile-or-fetch is synchronous
                # inside the first dispatch call — attribute it now
                self._emit_program_build("chunk", chunk_snap)
                ledger_pending = False
            self._note_watchdog_wall(t1 - t0, wd_snap)
            lat = self._lat
            lat["chunks"] += 1
            lat["dispatch_sec"] += t_disp - t0
            if dev_sec is not None:
                lat["device_sec"] += dev_sec
            fetch = t1 - t_dev
            lat["fetch_sec"] += fetch
            if lat["fetch_min"] is None or fetch < lat["fetch_min"]:
                lat["fetch_min"] = fetch
            if lat["t_first_sync"] is None:
                lat["t_first_sync"] = t1
            # Per-chunk stats observation (stateright_tpu/batch.py):
            # the fused multi-session engine demultiplexes its
            # per-session stat lanes here, EVERY chunk — the end-of-run
            # _consume_extra_stats is too late to peel a session that
            # settled mid-batch. Base: no-op.
            self._on_chunk_stats(
                s, carry, chunk_no, t0, t1, t_disp - t0, t1 - t_dev
            )
            if tracer is not None:
                from ..memplan import device_bytes_in_use

                mem_now, src = device_bytes_in_use()
                if mem_now is not None:
                    mem_src = src
                    mem_polls += 1
                    mem_peak = (mem_now if mem_peak is None
                                else max(mem_peak, mem_now))
                waves_now = int(s[4])
                n_waves = waves_now - prev_waves
                rows = self._wave_log_rows(s, n_props)
                srows = self._shard_log_rows(shard_log)
                # health layer: straggler detection over this chunk's
                # per-shard wave-log rows (no-op unless configured)
                self._note_shard_health(
                    None if srows is None else srows[:, :n_waves],
                    prev_waves,
                )
                tracer.record_chunk(
                    chunk=chunk_idx,
                    wave0=prev_waves,
                    t0=t0,
                    t1=t1,
                    dispatch_sec=t_disp - t0,
                    device_sec=dev_sec,
                    fetch_sec=t1 - t_dev,
                    n_waves=n_waves,
                    wave_rows=(None if rows is None
                               else rows[:n_waves]),
                    pairs_valid=self._wave_log_pairs_valid(),
                    shard_rows=(None if srows is None
                                else srows[:, :n_waves]),
                    mem_bytes=mem_now,
                )
                prev_waves = waves_now
                chunk_idx += 1
                # Property verdict timeline (round 14): the carried
                # disc_found lanes are cumulative, so the first chunk
                # whose stats show a property discovered IS the
                # moment the verdict became host-visible — the honest
                # settle point (at level="default" the granularity is
                # the chunk; level="deep" makes it the exact wave).
                if n_props:
                    disc = s[11:11 + n_props]
                    for i, prop in enumerate(props):
                        if disc[i] and prop.name not in verdicts_seen:
                            verdicts_seen.add(prop.name)
                            tracer.event(
                                "verdict",
                                property=prop.name,
                                expectation=(
                                    prop.expectation.name.lower()
                                ),
                                kind="discovery",
                                wave=int(s[4]),
                                depth=int(s[3]),
                                chunk=chunk_idx - 1,
                            )
            done = bool(s[0])
            self._total_states = int(s[6]) | (int(s[7]) << 32)
            self._unique_states = int(s[8])
            self._max_depth = max(self._max_depth, int(s[3]))
            self.metrics = dict(
                frontier_size=int(s[5]),
                occupancy=self._unique_states / self.total_capacity,
                dedup_ratio=(
                    1.0 - self._unique_states / self._total_states
                    if self._total_states
                    else 0.0
                ),
                waves=int(s[4]),
            )
            if mem_peak is not None:
                self.metrics["device_peak_bytes"] = mem_peak
            overflow_msg = self._overflow_message(s)
            if overflow_msg is not None:
                # Surface the engine-variant peak metrics (e.g.
                # max_wave_candidates) before raising — the overflow
                # messages point at them, and the auto-budget retry
                # sizes from them.
                self._consume_extra_stats(s[11 + 3 * n_props:])
                # Record discoveries BEFORE raising: with a
                # violation-gated closure bound (e.g. the register
                # models' linearizable-expansion history bound), the
                # violating state's own successors are unrepresentable
                # — truncation fires in the same chunk that finds the
                # counterexample, and the counterexample is the thing
                # the check exists to surface. It stays available on
                # the checker (discoveries()/discovered_property_names)
                # after catching the raise.
                self._record_discoveries(s, props)
                if self._discovered_fps:
                    overflow_msg += (
                        "  Discoveries recorded before truncation "
                        f"(valid counterexamples): "
                        f"{sorted(self._discovered_fps)} — read them "
                        "via discovered_property_names() / "
                        "discovery_fingerprints() after catching this "
                        "error."
                    )
                if tracer is not None:
                    # the overflow attempt's watermark still lands
                    # (the auto-budget retry re-runs inside the same
                    # trace run; last watermark wins in the views)
                    self._emit_memory_watermark(
                        tracer, mem_peak, mem_src, mem_polls
                    )
                raise RuntimeError(overflow_msg)
            # Checkpoint at THE EXISTING sync (the stats readback
            # above already blocked — the carry download piggybacks,
            # no new sync point): every ``checkpoint_every`` chunks,
            # the whole chunk carry lands as an atomic snapshot
            # (stateright_tpu/checkpoint.py). Never on a completed or
            # overflowed chunk — a clean completion needs no snapshot
            # and an overflowed carry is not a resume point.
            if (self.checkpoint_every and not done
                    and (chunk_no + 1) % self._ckpt_cadence() == 0):
                from .. import checkpoint as _ckpt

                t_ck = time.monotonic()
                _ckpt.write_snapshot(
                    self, carry, self.checkpoint_path,
                    chunk=chunk_no, wave=int(s[4]),
                    depth=int(s[3]), unique=int(s[8]),
                )
                self._note_snapshot_wall(
                    time.monotonic() - t_ck, t1 - t0
                )
            # fault-injection seam: the chunk boundary — AFTER the
            # snapshot write, so an injected kill here proves the
            # committed-snapshot sequencing a real preemption sees
            faultinject.fire("chunk_boundary", chunk_no,
                             shards=self._fault_shards())
            chunk_no += 1
            if not done:
                self._maybe_warn_occupancy(self.metrics["occupancy"])
            if done:
                break
            if reporter is not None:
                reporter.report_checking(
                    ReportData(
                        total_states=self._total_states,
                        unique_states=self._unique_states,
                        max_depth=self._max_depth,
                        duration_sec=self.duration_sec(),
                        done=False,
                    )
                )

        if tracer is not None:
            self._emit_memory_watermark(
                tracer, mem_peak, mem_src, mem_polls
            )
        # Keep device handles; download lazily only if a path is
        # reconstructed (_build_generated).
        self._capture_final(carry)
        if getattr(self, "keep_final_carry", False):
            # Tooling hook (tools/profile_stages.py): stash the whole
            # final carry so a stage profiler can rerun wave stages on
            # REAL mid-run frontier/visited data (spawn, set the
            # attribute, then join).
            self._final_carry = carry
        self._consume_extra_stats(s[11 + 3 * n_props :])
        self._record_discoveries(s, props, reconstruct=True)

    def _overflow_message(self, s) -> Optional[str]:
        """The engine's overflow verdict from one chunk's packed stats
        (one home — the tiered takeover loop raises through the same
        messages the untiered chunk loop does)."""
        if bool(s[1]):
            return (
                f"visited table overflow (capacity={self.capacity}); "
                "re-run with a larger capacity"
            )
        if bool(s[2]):
            return (
                f"frontier overflow: a wave produced more than "
                f"{self.frontier_capacity} new states; re-run with a "
                "larger frontier_capacity"
            )
        if bool(s[9]):
            return self._cand_overflow_message()
        if bool(s[10]):
            return (
                "encoding-bound overflow: a successor was pruned by "
                "an internal encoding bound (e.g. a compiled envelope "
                "count reached 128, a declared FIFO queue bound, or "
                "an un-harvested history transition) — the state "
                "space would be silently truncated. Bound the model "
                "(boundary/closure bounds) or use an encoding with "
                "wider fields."
            )
        return None

    def _tier_takeover(self, carry, n0, chunk_no, reporter):
        """Tiered-visited-set hook (stateright_tpu/tier.py), called at
        the top of every chunk iteration: None = stay on the untiered
        chunk loop. The sort-merge engines override — once the hot
        ceiling is crossed or resumed cold runs exist, they spill and
        run the deferred-commit tiered loop to completion, returning
        the final ``(carry, stats)``."""
        return None

    def _record_discoveries(self, s, props, reconstruct=False) -> None:
        """Parse the cumulative discovery lanes out of a chunk's packed
        stats (disc_found persists in the device carry, so ANY chunk's
        stats hold the discoveries so far). Paths are reconstructed
        only on the clean-completion call: on the overflow path the
        parent log may be mid-wave."""
        n_props = len(props)
        disc_found = s[11 : 11 + n_props]
        disc_lo = s[11 + n_props : 11 + 2 * n_props]
        disc_hi = s[11 + 2 * n_props : 11 + 3 * n_props]
        for i, prop in enumerate(props):
            if disc_found[i]:
                fp = _fp_int(disc_lo[i], disc_hi[i])
                self._discovered_fps[prop.name] = fp
                if reconstruct and self.track_paths:
                    self._discoveries[prop.name] = self._reconstruct(fp)

    def _program_cache_key(self, n0: int):
        """The compiled-program identity (one home; engine variants
        contribute via ``_cache_extras``). None when the encoding
        declares no ``cache_key`` — shapes alone can't distinguish
        different transition functions, so such programs are never
        shared (and their memory analysis is never disk-cached)."""
        enc = self.encoded
        key_fn = getattr(enc, "cache_key", None)
        if key_fn is None:
            return None
        return (
            type(self),
            self._cache_extras(),
            type(enc),
            key_fn(),
            enc.width,
            enc.max_actions,
            self.frontier_capacity,
            self.capacity,
            self.cand_capacity,
            self.probe_rounds,
            self.waves_per_sync,
            self.track_paths,
            n0,
            self.builder._target_state_count,
            self.builder._target_max_depth,
            tuple(
                (p.name, p.expectation)
                for p in self.model.properties()
            ),
        )

    def _lookup_programs(self, n0: int):
        """Build-or-fetch the compiled device programs (cache key:
        :meth:`_program_cache_key`). The per-class build info the
        memory ledger reads (``_build_info`` — ladder-class staging
        shapes, CHUNKED-mode records) rides the cache entry: a
        cache-hit checker instance never ran ``_build_programs``, but
        its plan must still be a function of the ladder classes.

        Compile-cache ledger (round 14): this seam is the FIRST tier.
        An in-process hit emits its ``program_build`` row here (the
        whole seed+chunk pair fetched, no XLA work possible). A miss
        only TRACES here — jit compilation is lazy — so it arms
        ``_fresh_build``: the seed and chunk rows then land at their
        real compile sites in ``_run`` (seed_upload, chunk-0
        dispatch), tier-attributed from the monitor deltas."""
        # Admission-time pre-warm (stateright_tpu/serve.py): when the
        # service kicked this build on a worker thread, join it first
        # so the worker's _CHUNK_CACHE insert and this lookup cannot
        # race — the run then takes the in-process-hit path.
        pw = getattr(self, "_prewarm_wait", None)
        if pw is not None:
            pw()
        _enable_persistent_cache()
        cache_key = self._program_cache_key(n0)
        self._program_key_hash = _key_hash(cache_key)
        tracer = self._tracer
        t0 = time.monotonic()
        if cache_key is None:
            self._fresh_build = True
            return self._build_programs(n0)
        if cache_key not in _CHUNK_CACHE:
            self._fresh_build = True
            programs = self._build_programs(n0)
            # _carry_pspecs rides the cache like _build_info: a
            # cache-hit instance never ran _build_programs, but a
            # resume into it must still place snapshot buffers with
            # the mesh shardings the programs were built for.
            _CHUNK_CACHE[cache_key] = (
                programs,
                getattr(self, "_build_info", None),
                getattr(self, "_carry_pspecs", None),
            )
        else:
            self._fresh_build = False
            if tracer is not None:
                tracer.event(
                    "program_build", program="programs",
                    tier="in_process", key=self._program_key_hash,
                    wall_sec=round(time.monotonic() - t0, 6),
                    cold_sec=0.0,
                )
        programs, self._build_info, self._carry_pspecs = \
            _CHUNK_CACHE[cache_key]
        return programs

    def _emit_program_build(self, program: str, snap: tuple) -> None:
        """One compile-cache ledger row from a monitor delta (the
        build-or-fetch XLA performed since ``snap``); no-op untraced."""
        tracer = self._tracer
        if tracer is None:
            return
        tier, wall, cold = _resolve_tier(_monitor_delta(snap))
        tracer.event(
            "program_build", program=program, tier=tier,
            key=getattr(self, "_program_key_hash", None),
            wall_sec=round(wall, 6),
            cold_sec=(None if cold is None else round(cold, 6)),
        )

    # -- memory observability (stateright_tpu/memplan.py) ------------------

    def _plan_sharded_names(self) -> tuple:
        """Carry leaves split across the mesh (their ledger rows get
        ``per_shard_bytes = bytes / n_shards``); single-chip engines
        shard nothing."""
        return ()

    def _memory_plan(self, n0: int, with_compiled: bool = False) -> dict:
        """The resident-buffer ledger: every chunk-carry buffer this
        engine keeps device-resident between syncs, derived from the
        seed program's output spec via ``jax.eval_shape`` (no
        allocation, no compile — the declaration cannot drift from
        the allocation, which the plan-vs-``nbytes`` test pins on
        real arrays), plus the per-ladder-class staging ledger
        recorded at program build (``_build_info``) and — when
        ``with_compiled`` — XLA's own ``memory_analysis()`` of the
        chunk program (null where the backend doesn't report it)."""
        import jax
        import jax.numpy as jnp

        from .. import memplan

        seed_fn, chunk_fn = self._programs
        spec = jax.eval_shape(
            seed_fn,
            jax.ShapeDtypeStruct((n0, self.encoded.width), jnp.uint32),
        )
        n_shards = getattr(self, "n_shards", 1)
        resident = memplan.plan_entries(
            spec, sharded=self._plan_sharded_names(), n_shards=n_shards
        )
        info = getattr(self, "_build_info", None) or {}
        classes = info.get("classes", [])
        v_classes = info.get("v_classes", [])
        # Class staging shapes are PER DEVICE (the shard_map body's
        # view on mesh engines; the whole device on single-chip) —
        # the global peak multiplies by the mesh width.
        class_peak = n_shards * max(
            (c.get("staging_bytes", 0) for c in classes), default=0
        )
        merge_peak = n_shards * max(
            (v.get("merge_scratch_bytes", 0) for v in v_classes),
            default=0,
        )
        compiled = None
        if with_compiled:
            # Compile-cache ledger row for the AOT memory-analysis
            # compile (round 14): memplan reports which of ITS caches
            # served the result; when the AOT pass actually ran, the
            # monitor delta decides whether XLA compiled cold or
            # loaded from the persistent disk cache.
            served: dict = {}
            snap = _monitor_snapshot()
            token = self._program_cache_key(n0)
            if token is None:
                t0 = time.monotonic()
                try:
                    compiled = memplan.compiled_memory(
                        chunk_fn.lower(spec).compile()
                    )
                    served = dict(tier="aot",
                                  wall=time.monotonic() - t0)
                except Exception:
                    compiled = None
            else:
                compiled = memplan.compiled_memory_analysis(
                    chunk_fn, spec, token,
                    on_build=lambda tier, wall: served.update(
                        tier=tier, wall=wall
                    ),
                )
            tracer = self._tracer
            if tracer is not None and served:
                if served["tier"] == "aot":
                    tier, wall, cold = _resolve_tier(
                        _monitor_delta(snap)
                    )
                    # the AOT pass ran but XLA did no compile-or-fetch
                    # (can't happen in practice): keep the honest wall
                    wall = wall or served["wall"]
                else:
                    tier, wall, cold = served["tier"], served["wall"], 0.0
                tracer.event(
                    "program_build", program="memory_analysis",
                    tier=tier,
                    key=getattr(self, "_program_key_hash", None),
                    wall_sec=round(wall, 6),
                    cold_sec=(None if cold is None
                              else round(cold, 6)),
                )
        resident_bytes = memplan.plan_total(resident)
        return dict(
            engine=type(self).__name__,
            n_shards=n_shards,
            resident=resident,
            resident_bytes=resident_bytes,
            classes=classes,
            v_classes=v_classes,
            class_peak_bytes=int(class_peak + merge_peak),
            compiled=compiled,
            total_bytes=int(resident_bytes + class_peak + merge_peak),
        )

    def _ckpt_cadence(self) -> int:
        """The effective chunks-per-snapshot: the literal
        ``checkpoint_every``, or — at ``"auto"`` — the cadence
        :func:`checkpoint.auto_cadence` derived from the measured
        snapshot and chunk walls (every chunk until both exist)."""
        if self.checkpoint_every == "auto":
            return self._ckpt_auto_every
        return int(self.checkpoint_every)

    def _note_snapshot_wall(self, snap_sec: float,
                            chunk_sec: float) -> None:
        """Feed the measured walls into the auto-cadence policy
        (``checkpoint_every="auto"``): re-derive the cadence after
        every snapshot so it tracks the run's real chunk wall. A
        cadence change lands as a ``checkpoint_cadence`` event."""
        if self.checkpoint_every != "auto":
            return
        from .. import checkpoint as _ckpt
        from .. import telemetry

        new = _ckpt.auto_cadence(snap_sec, chunk_sec)
        if new != self._ckpt_auto_every:
            telemetry.emit(
                "checkpoint_cadence",
                old=int(self._ckpt_auto_every), new=int(new),
                snapshot_sec=round(snap_sec, 6),
                chunk_sec=round(chunk_sec, 6),
            )
            self._ckpt_auto_every = new

    def _emit_memory_watermark(self, tracer, peak, source,
                               polls) -> None:
        """The run-end watermark event: device peak bytes (from the
        per-chunk polls), visited/budget headroom, and the capacity
        projection — the numbers the tiered-visited-set and
        HBM-staging decisions (ROADMAP directions 1b/2b) read.
        ``cold_tier_bytes`` (round 16) prices the host-DRAM cold tier
        so capacity headroom accounts for BOTH tiers; the tiered
        takeover loop's own polls merge in through ``_tier_mem``."""
        tmem = getattr(self, "_tier_mem", None)
        if tmem is not None:
            t_peak, t_src, t_polls = tmem
            if t_peak is not None:
                peak = (t_peak if peak is None
                        else max(int(peak), int(t_peak)))
                source = source or t_src
            polls = int(polls) + int(t_polls)
        tier = self._tier_headroom()
        tracer.event(
            "memory_watermark",
            source=source,
            device_peak_bytes=(None if peak is None else int(peak)),
            polls=int(polls),
            cold_tier_bytes=(None if tier is None
                             else tier.get("cold_bytes_total")),
            headroom=self._memory_headroom(),
            projection=self._memory_projection(),
        )

    def _tier_headroom(self):
        """Cold-tier accounting for the watermark/headroom views
        (None on engines without a tiered visited set, and on
        sort-merge runs that never spilled)."""
        return None

    def _visited_bytes_per_row(self) -> int:
        """Logical device bytes per visited entry: two uint32 key-limb
        lanes, plus the parent-forest lanes when paths are tracked."""
        return 8 + (8 if self.track_paths else 0)

    def _memory_headroom(self) -> dict:
        """Host-side visited/budget byte accounting for the watermark:
        observed unique rows vs capacity, priced in bytes, plus the
        persisted auto-budget join on engines that have one."""
        bpr = self._visited_bytes_per_row()
        cap = self.total_capacity
        u = self._unique_states
        return dict(
            visited_rows=int(u),
            visited_capacity=int(cap),
            occupancy=(round(u / cap, 4) if cap else None),
            bytes_per_row=bpr,
            visited_used_bytes=int(u * bpr),
            visited_capacity_bytes=int(cap * bpr),
            budget=self._budget_headroom(),
            tier=self._tier_headroom(),
        )

    def _budget_headroom(self):
        """Joined from the persisted auto-budget store on engines
        that keep one (the sort-merge ``cand_capacity="auto"`` path);
        None elsewhere."""
        return None

    def _memory_projection(self) -> dict:
        """Predicted bytes at the next capacity step. The hash-table
        engine has no ladder: open addressing degrades past probe
        pressure and the remedy is doubling, so the projection prices
        capacity x2. (The sort-merge engines override this with the
        next-visited-ladder-class prediction — the number that
        decides when V stops fitting VMEM.)"""
        bpr = self._visited_bytes_per_row()
        nxt = 2 * self.total_capacity
        return dict(
            kind="capacity_x2",
            next_rows=int(nxt),
            next_visited_bytes=int(nxt * bpr),
        )

    def latency_accounting(self) -> Optional[dict]:
        """The run's host-side wall split, available UNTRACED (the
        round-14 latency layer's bench seam): chunk count, total
        dispatch wall (async ``chunk_fn`` calls), total host-blocked
        sync wall (the blocking stats readbacks — at the default trace
        level this includes the device wait hidden behind the sync),
        the per-chunk sync floor (min fetch), and time-to-first-wave.
        Traced runs get the richer ``latency_profile`` event on top;
        this is what bench.py embeds per lane so even untraced BENCH
        artifacts carry sync-floor attribution. None before a run."""
        lat = self._lat
        if not lat or not lat["chunks"]:
            return None
        return dict(
            chunks=lat["chunks"],
            dispatch_sec=round(lat["dispatch_sec"], 6),
            fetch_sec=round(lat["fetch_sec"], 6),
            fetch_min_sec=round(lat["fetch_min"], 6),
            device_sec=(round(lat["device_sec"], 6)
                        if lat["device_sec"] else None),
            time_to_first_wave_sec=round(
                lat["t_first_sync"] - lat["t_start"], 6
            ),
        )

    def _consume_extra_stats(self, extra: np.ndarray) -> None:
        """Hook for engine variants that append metric lanes after the
        per-property discovery lanes (see parallel/engine.py)."""

    # -- fused multi-session hooks (stateright_tpu/batch.py) ---------------
    #
    # The wave batcher subclasses this engine and extends the device
    # program through these four seams instead of forking it: extra
    # carry lanes at seed, per-wave lane accounting (and per-session
    # settlement masking) in the wave body, extra packed-stat lanes at
    # the chunk sync, and a host-side per-chunk observation point for
    # demultiplexing. All four are no-ops here — the base program and
    # its compiled cache entries are byte-identical with the hooks in
    # place (the subclass is a distinct _program_cache_key type).

    def _seed_extra(self, out: dict, init_rows, jnp) -> dict:
        """Extra carry keys merged into the seed program's output."""
        return {}

    def _body_extra(self, c: dict, out: dict, ctx: dict, jnp) -> dict:
        """Extra (or overridden) carry keys merged into one wave's
        output. Must return every key ``_seed_extra`` added — a
        ``lax.while_loop`` carry's structure is fixed. ``ctx`` exposes
        the wave internals: ``ex`` (expand_frontier output), ``b_ext``/
        ``b_val`` (compacted candidate payload + validity), ``is_new``,
        ``new_count``, ``n_cand``."""
        return {}

    def _stats_extra(self, c: dict, jnp) -> list:
        """Extra 1-D uint32 lanes appended to the packed chunk stats
        (host side: ``s[11 + 3 * n_props:]``)."""
        return []

    def _on_chunk_stats(self, s, carry, chunk_no: int, t0: float,
                        t1: float, dispatch_sec: float,
                        fetch_sec: float) -> None:
        """Host observation of one chunk's packed stats, called every
        chunk (unlike ``_consume_extra_stats``, which only fires at
        run end/overflow — too late to peel a settled session out of
        a live batch)."""

    def _wave_log_enabled(self) -> bool:
        """Whether the chunk carry includes the per-wave trace log.
        Resolved from the tracer ``_run`` attaches BEFORE program
        build, so the flag, the compiled program, and the stats parser
        can't disagree. Engine variants that implement a log gate the
        carry field (and their cache key) on this; the base hash-table
        engine compiles no log either way."""
        return self._tracer is not None

    def _wave_log_rows(self, s: np.ndarray, n_props: int):
        """Hook: the device wave-log rows out of a chunk's packed
        stats ([waves_per_sync, telemetry.WAVE_LOG_LANES] int array),
        or None when this engine keeps no per-wave log (the hash-table
        engine — its chunks still produce chunk/span events)."""
        return None

    def _shard_log_rows(self, shard_log):
        """The per-shard mesh wave log out of a chunk's third output,
        unpacked from its device-axis concatenation to
        ``[n_shards, waves_per_sync, telemetry.SHARD_LOG_LANES]``.
        ``shard_log`` is None on single-chip engines and untraced runs
        (only the sharded engines return a third chunk output, so
        ``n_shards`` is always defined when this reshapes)."""
        if shard_log is None:
            return None
        from ..telemetry import SHARD_LOG_LANES as SL

        return np.asarray(shard_log).reshape(
            self.n_shards, self.waves_per_sync, SL
        )

    def _wave_log_pairs_valid(self) -> bool:
        """Hook: whether wave-log lane 1 really is the enabled-pair
        popcount (False on engines that can't see it from the log
        wrapper; the tracer then records ``enabled_pairs: null``)."""
        return True

    def _lane_config(self) -> dict:
        lane = super()._lane_config()
        lane.update(
            encoding=type(self.encoded).__name__,
            width=self.encoded.width,
            max_actions=self.encoded.max_actions,
            capacity=self.capacity,
            frontier_capacity=self.frontier_capacity,
            cand_capacity=self.cand_capacity,
            waves_per_sync=self.waves_per_sync,
            track_paths=self.track_paths,
            # per-entry visited cost (the memory ledger's number):
            # what telemetry.shard_balance prices occupancy warnings
            # with, the way dest_tile_lanes prices routed bytes
            visited_row_bytes=self._visited_bytes_per_row(),
        )
        return lane

    def _capture_final(self, carry) -> None:
        """Stash device handles needed for lazy path reconstruction."""
        self._final_tables = (
            carry["t_lo"],
            carry["t_hi"],
            carry["p_lo_t"],
            carry["p_hi_t"],
        )

    def _cache_extras(self) -> tuple:
        """Engine-variant parameters that distinguish compiled programs
        (see the compiled-chunk cache in _run)."""
        return ()

    def _maybe_warn_occupancy(self, occupancy: float) -> None:
        """Open addressing degrades before it overflows; warn early.
        (The sort-merge engine overrides this: its visited array is
        exact-capacity with no probe pressure.) The message comes from
        the shared formatter (stateright_tpu/occupancy.py) the mesh
        observability layer's per-shard occupancy metric also uses."""
        from ..occupancy import occupancy_warning

        msg = occupancy_warning(
            occupancy,
            used=self._unique_states,
            capacity=self.total_capacity,
            # the ledger's per-entry cost (round 12): the warning
            # prices the fill in bytes, not just rows
            bytes_per_row=self._visited_bytes_per_row(),
        )
        if msg is not None:
            import warnings

            warnings.warn(
                msg,
                RuntimeWarning,
                # 3 = the user's spawn/join call site for the direct
                # _run depth; engine subclasses share that depth today.
                stacklevel=3,
            )

    def _cand_overflow_message(self) -> str:
        return (
            f"candidate-buffer overflow: a wave generated more than "
            f"{self.cand_capacity} valid successors; re-run with a "
            "larger cand_capacity (or None to disable compaction)"
        )

    # -- reconstruction ----------------------------------------------------

    def _build_generated(self) -> dict[int, Optional[int]]:
        """Materialize the child→parent fingerprint map from the final
        device tables (one transfer already done; host-side unpack)."""
        if self.generated is None:
            # The one (lazy) table download.
            t_lo, t_hi, p_lo, p_hi = (
                np.asarray(a) for a in self._final_tables
            )
            occupied = (t_lo != 0) | (t_hi != 0)
            child = _combine64(t_lo[occupied], t_hi[occupied])
            parent = _combine64(p_lo[occupied], p_hi[occupied])
            self.generated = {
                int(c): (int(p) if p else None)
                for c, p in zip(child.tolist(), parent.tolist())
            }
        return self.generated

    def _vec_fp(self, row: np.ndarray) -> int:
        # Symmetry: visited keys are canonical fingerprints, so host
        # replay must canonicalize the encoded row with the SAME
        # (xp-generic) kernel before fingerprinting — bit-equal to
        # what the device wrote, or path reconstruction would miss.
        if self.sym_spec is not None:
            from ..ops.canonical import canonicalize_rows

            row = canonicalize_rows(
                self.sym_spec, np.asarray(row, np.uint32), np
            )
        lo, hi = fingerprint_u32v(row.reshape(1, -1), np)
        return _fp_int(lo[0], hi[0])

    def _reconstruct(self, fp: int) -> Path:
        """Walk the parent forest, then replay the HOST model matching
        device fingerprints of encoded successors (bfs.rs:371-400 +
        path.rs:20-97, with the encoder as the bridge)."""
        from .. import telemetry

        with telemetry.span("counterexample_reconstruction",
                            fingerprint=f"{fp:#x}"):
            return self._reconstruct_inner(fp)

    def _reconstruct_inner(self, fp: int) -> Path:
        from .. import telemetry

        # The reconstruction wall, split (round 14): draining the
        # device parent log (the one lazy table download + host
        # unpack) vs replaying the host model to decode fingerprints
        # back into states — the two halves scale differently
        # (transfer-bound vs host-CPU-bound), so time-to-verdict
        # attribution needs them apart.
        with telemetry.span("cex_parent_drain"):
            generated = self._build_generated()
        with telemetry.span("cex_host_decode"):
            return self._decode_path(generated, fp)

    def _decode_path(self, generated, fp: int) -> Path:
        fps = [fp]
        while True:
            parent = generated.get(fps[-1])
            if parent is None:
                break
            fps.append(parent)
        fps.reverse()

        model = self.model
        enc = self.encoded
        state = None
        for init_state in model.init_states():
            if self._vec_fp(np.asarray(enc.encode(init_state), np.uint32)) == fps[0]:
                state = init_state
                break
        if state is None:
            raise RuntimeError(
                f"no init state encodes to fingerprint {fps[0]:#x}; "
                "encode()/init_vecs() disagree"
            )
        steps = []
        for next_fp in fps[1:]:
            found = False
            for action in model.actions(state):
                next_state = model.next_state(state, action)
                if next_state is None:
                    continue
                vec = np.asarray(enc.encode(next_state), np.uint32)
                if self._vec_fp(vec) == next_fp:
                    steps.append((state, action))
                    state = next_state
                    found = True
                    break
            if not found:
                raise RuntimeError(
                    f"no host successor encodes to {next_fp:#x}: the "
                    "vectorized step_vec disagrees with the host model"
                )
        steps.append((state, None))
        return Path(steps)
