"""The TPU wave engine: breadth-first search as vectorized XLA programs.

This is the performance core of the framework — the re-design of the
reference's thread-pool BFS (src/checker/bfs.rs + src/job_market.rs)
for accelerators. One *wave* processes the entire frontier as a single
jitted device program:

    frontier ──vmap step──▶ padded successors ──fingerprint──▶
    sort+unique ──▶ table insert-if-absent ──▶ compact new frontier

Property predicates are evaluated as bitmaps over the frontier;
``EventuallyBits`` ride along each frontier row exactly as in the
reference (checker.rs:559-566, including the documented revisit
false-negative, bfs.rs:285-303). The host keeps only what the
reference keeps on the host side too: the child→parent fingerprint
forest for counterexample reconstruction (bfs.rs:28-29, 371-400) and
discovery bookkeeping. Path recovery replays the *host* model and
matches device fingerprints of encoded successors — which doubles as a
continuous differential check that the encoding agrees with the host
semantics.

Multi-chip scale-out (sharded frontier + all-to-all shuffle by
fingerprint, replacing job_market.rs work stealing) lives in
:mod:`stateright_tpu.parallel` and wraps this same wave body in
``shard_map``.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ..checker import Checker, CheckerBuilder
from ..encoding import EncodedModel
from ..model import Expectation
from ..ops.fingerprint import fingerprint_u32v
from ..ops.hashset import DeviceHashSet, insert, sort_unique
from ..path import Path
from ..report import ReportData, Reporter

_SENTINEL = 0xFFFFFFFF  # sort key for invalid successor rows

# Wave programs are expensive to compile (the K-successor builder and
# probe loop unroll into a large XLA graph) and identical across
# checker instances with the same encoding and shapes — cache them.
_WAVE_CACHE: dict = {}
_PERSISTENT_CACHE_SET = False


def _enable_persistent_cache() -> None:
    """Route XLA compilations through a disk cache so repeated runs
    (tests, CLI re-invocations) skip the multi-second compile."""
    global _PERSISTENT_CACHE_SET
    if _PERSISTENT_CACHE_SET:
        return
    _PERSISTENT_CACHE_SET = True
    import os

    import jax

    if jax.config.jax_compilation_cache_dir is None:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.expanduser("~/.cache/stateright_tpu_xla"),
        )
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def _fp_int(lo, hi) -> int:
    return (int(hi) << 32) | int(lo)


def _combine64(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    return (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)


class TpuBfsChecker(Checker):
    """``CheckerBuilder.spawn_tpu()`` — the reference's ``spawn_bfs``
    offloaded to a device (BASELINE.json north star)."""

    def __init__(
        self,
        builder: CheckerBuilder,
        encoded: Optional[EncodedModel] = None,
        capacity: int = 1 << 16,
        frontier_capacity: Optional[int] = None,
        track_paths: bool = True,
    ):
        super().__init__(builder)
        if builder._symmetry is not None:
            raise ValueError("symmetry reduction requires spawn_dfs")
        if encoded is None:
            to_encoded = getattr(builder.model, "to_encoded", None)
            if to_encoded is None:
                raise ValueError(
                    "spawn_tpu requires an EncodedModel: pass encoded=... or "
                    "implement Model.to_encoded()"
                )
            encoded = to_encoded()
        self.encoded = encoded
        self.capacity = capacity
        self.frontier_capacity = frontier_capacity or capacity
        self.track_paths = track_paths
        #: child vec-fingerprint -> parent vec-fingerprint (None = init)
        self.generated: dict[int, Optional[int]] = {}
        #: property name -> fingerprint of the discovery state; always
        #: populated (drives early exit) even when track_paths=False
        #: suppresses Path materialization.
        self._discovered_fps: dict[str, int] = {}
        self._wave_fn = None

    def _all_discovered(self) -> bool:
        props = self.model.properties()
        return len(props) > 0 and all(
            p.name in self._discovered_fps for p in props
        )

    def discovered_property_names(self) -> set:
        """Names with a discovery — available even with
        ``track_paths=False`` (where full paths are not)."""
        self._ensure_run()
        return set(self._discovered_fps)

    def discoveries(self):
        if not self.track_paths and self._discovered_fps:
            raise RuntimeError(
                "paths unavailable with track_paths=False; use "
                "discovered_property_names(), or re-run with "
                "track_paths=True for counterexample traces"
            )
        return super().discoveries()

    # -- device program --------------------------------------------------

    def _build_wave(self):
        import jax
        import jax.numpy as jnp

        enc = self.encoded
        props = list(self.model.properties())
        n_props = len(props)
        evt_idx = [
            i for i, p in enumerate(props)
            if p.expectation == Expectation.EVENTUALLY
        ]
        if evt_idx and max(evt_idx) >= 32:
            # ebits ride in a uint32 lane; 1 << 32 would silently wrap.
            raise ValueError(
                "the TPU engine supports eventually properties only at "
                "property indices < 32; reorder properties() so eventually "
                f"properties come first (got index {max(evt_idx)})"
            )
        K, W, F = enc.max_actions, enc.width, self.frontier_capacity

        def wave(table: DeviceHashSet, frontier, fval, ebits, expand: bool):
            # Frontier digests (for parent pointers and discoveries).
            f_lo, f_hi = fingerprint_u32v(frontier, jnp)

            # Property bitmap over the frontier (bfs.rs:223-268).
            if n_props:
                cond = jax.vmap(enc.property_conditions_vec)(frontier)
                cond = cond & fval[:, None]
            else:
                cond = jnp.zeros((F, 0), dtype=bool)
            # Clear satisfied eventually-bits (checker.rs:559-566).
            for i in evt_idx:
                ebits = jnp.where(cond[:, i], ebits & ~jnp.uint32(1 << i), ebits)

            if expand:
                succs, valid = jax.vmap(enc.step_vec)(frontier)
                valid = valid & fval[:, None]
                bound = jax.vmap(
                    lambda row: jax.vmap(enc.within_boundary_vec)(row)
                )(succs)
                valid = valid & bound
            else:
                succs = jnp.zeros((F, K, W), dtype=jnp.uint32)
                valid = jnp.zeros((F, K), dtype=bool)

            # Terminal rows: no successors at all → surviving
            # eventually-bits are counterexamples (bfs.rs:317-324).
            # Depth-cut waves (expand=False) are not terminal.
            if expand:
                terminal = fval & ~jnp.any(valid, axis=1)
            else:
                terminal = jnp.zeros(F, dtype=bool)
            evt_cex = terminal & (ebits != 0)

            flat = succs.reshape(F * K, W)
            v = valid.reshape(F * K)
            c_lo, c_hi = fingerprint_u32v(flat, jnp)
            c_lo = jnp.where(v, c_lo, jnp.uint32(_SENTINEL))
            c_hi = jnp.where(v, c_hi, jnp.uint32(_SENTINEL))
            p_lo = jnp.repeat(f_lo, K)
            p_hi = jnp.repeat(f_hi, K)
            child_ebits = jnp.repeat(ebits, K)

            (s_lo, s_hi, order), first = sort_unique(c_lo, c_hi, jnp)
            v_sorted = v[order]
            active = first & v_sorted
            table, is_new, overflow = insert(table, s_lo, s_hi, active, jnp)

            # Compact new states into the next frontier. Non-new rows
            # scatter to index F*K, which is out of range for every
            # output buffer and dropped.
            new_count = jnp.sum(is_new)
            pos = jnp.cumsum(is_new) - 1
            scatter_pos = jnp.where(is_new, pos, F * K)
            next_frontier = jnp.zeros((F, W), dtype=jnp.uint32).at[
                scatter_pos
            ].set(flat[order], mode="drop")
            next_ebits = jnp.zeros(F, dtype=jnp.uint32).at[scatter_pos].set(
                child_ebits[order], mode="drop"
            )
            next_fval = jnp.arange(F) < new_count

            # Per-wave host transfer: new fingerprints + their parents.
            out_lo = jnp.zeros(F * K, dtype=jnp.uint32).at[scatter_pos].set(
                s_lo, mode="drop"
            )
            out_hi = jnp.zeros(F * K, dtype=jnp.uint32).at[scatter_pos].set(
                s_hi, mode="drop"
            )
            out_plo = jnp.zeros(F * K, dtype=jnp.uint32).at[scatter_pos].set(
                p_lo[order], mode="drop"
            )
            out_phi = jnp.zeros(F * K, dtype=jnp.uint32).at[scatter_pos].set(
                p_hi[order], mode="drop"
            )

            # Discovery summaries: one candidate fingerprint per property.
            def first_fp(mask):
                any_hit = jnp.any(mask)
                row = jnp.argmax(mask)
                return any_hit, f_lo[row], f_hi[row]

            disc_found = []
            disc_lo = []
            disc_hi = []
            for i, p in enumerate(props):
                if p.expectation == Expectation.ALWAYS:
                    mask = fval & ~cond[:, i]
                elif p.expectation == Expectation.SOMETIMES:
                    mask = cond[:, i]
                else:
                    mask = evt_cex & ((ebits & jnp.uint32(1 << i)) != 0)
                hit, lo_, hi_ = first_fp(mask)
                disc_found.append(hit)
                disc_lo.append(lo_)
                disc_hi.append(hi_)
            disc_found = (
                jnp.stack(disc_found) if disc_found else jnp.zeros(0, bool)
            )
            disc_lo = (
                jnp.stack(disc_lo) if disc_lo else jnp.zeros(0, jnp.uint32)
            )
            disc_hi = (
                jnp.stack(disc_hi) if disc_hi else jnp.zeros(0, jnp.uint32)
            )

            total_generated = jnp.sum(v)
            return dict(
                table=table,
                frontier=next_frontier,
                fval=next_fval,
                ebits=next_ebits,
                new_count=new_count,
                total_generated=total_generated,
                overflow=jnp.any(overflow),
                new_lo=out_lo,
                new_hi=out_hi,
                par_lo=out_plo,
                par_hi=out_phi,
                disc_found=disc_found,
                disc_lo=disc_lo,
                disc_hi=disc_hi,
            )

        return jax.jit(wave, static_argnames=("expand",))

    # -- host orchestration ----------------------------------------------

    def _run(self, reporter: Optional[Reporter] = None) -> None:
        import jax.numpy as jnp

        enc = self.encoded
        props = list(self.model.properties())
        F, W = self.frontier_capacity, enc.width
        target_states = self.builder._target_state_count
        target_depth = self.builder._target_max_depth
        if self.builder._visitor is not None:
            raise ValueError(
                "visitors require a host checker (spawn_bfs/spawn_dfs); the "
                "TPU engine keeps full states on device only"
            )

        if self._wave_fn is None:
            _enable_persistent_cache()
            # Share compiled waves between checkers only when the
            # encoding declares an identity (cache_key): shapes alone
            # can't distinguish different transition functions.
            key_fn = getattr(enc, "cache_key", None)
            if key_fn is not None:
                cache_key = (
                    type(enc),
                    key_fn(),
                    enc.width,
                    enc.max_actions,
                    F,
                    self.capacity,
                    tuple((p.name, p.expectation) for p in props),
                )
                if cache_key not in _WAVE_CACHE:
                    _WAVE_CACHE[cache_key] = self._build_wave()
                self._wave_fn = _WAVE_CACHE[cache_key]
            else:
                self._wave_fn = self._build_wave()

        # Seed: encoded init states, deduped, inserted into the table.
        # (Init states are assumed within the boundary, as is true of
        # every reference workload; successors are boundary-filtered on
        # device each wave.)
        init = np.asarray(enc.init_vecs(), dtype=np.uint32).reshape(-1, W)
        seen = set()
        rows = []
        for row in init:
            fp = self._vec_fp(row)
            if fp not in seen:
                seen.add(fp)
                rows.append(row)
                self.generated[fp] = None
        init = np.stack(rows) if rows else np.zeros((0, W), np.uint32)
        n0 = init.shape[0]
        if n0 > F:
            raise ValueError(f"frontier capacity {F} < {n0} init states")
        self._total_states += n0
        self._unique_states += n0

        frontier = jnp.zeros((F, W), dtype=jnp.uint32).at[:n0].set(init)
        fval = jnp.arange(F) < n0
        ebits = jnp.where(
            fval, jnp.uint32(self._eventually_bits_init()), jnp.uint32(0)
        )
        # Seed the table host-side, then transfer once.
        lo0, hi0 = fingerprint_u32v(init, np)
        (slo, shi, _), first = sort_unique(
            np.asarray(lo0, np.uint32), np.asarray(hi0, np.uint32), np
        )
        table_np = DeviceHashSet.empty(self.capacity, np)
        table_np, _, seed_overflow = insert(table_np, slo, shi, first, np)
        if bool(np.any(seed_overflow)):
            raise RuntimeError(
                f"visited table overflow while seeding {n0} init states "
                f"(capacity={self.capacity}); re-run with a larger capacity"
            )
        table = DeviceHashSet(jnp.asarray(table_np.lo), jnp.asarray(table_np.hi))

        depth = 1
        while True:
            self._max_depth = max(self._max_depth, depth)
            expand = not (target_depth is not None and depth >= target_depth)
            out = self._wave_fn(table, frontier, fval, ebits, expand=expand)
            table = out["table"]

            if bool(out["overflow"]):
                raise RuntimeError(
                    f"visited table overflow (capacity={self.capacity}); "
                    "re-run with a larger capacity"
                )

            new_count = int(out["new_count"])
            self._total_states += int(out["total_generated"])
            self._unique_states += new_count

            if self.track_paths and new_count:
                # Vectorized parent-map update: table-new keys cannot
                # already be present (the table mirrors `generated`).
                child = _combine64(
                    np.asarray(out["new_lo"][:new_count]),
                    np.asarray(out["new_hi"][:new_count]),
                )
                parent = _combine64(
                    np.asarray(out["par_lo"][:new_count]),
                    np.asarray(out["par_hi"][:new_count]),
                )
                self.generated.update(zip(child.tolist(), parent.tolist()))

            # Discoveries (host side, mirrors bfs.rs discovery
            # recording) — after the parent map grew this wave.
            disc_found = np.asarray(out["disc_found"])
            disc_lo = np.asarray(out["disc_lo"])
            disc_hi = np.asarray(out["disc_hi"])
            for i, prop in enumerate(props):
                if disc_found[i] and prop.name not in self._discovered_fps:
                    fp = _fp_int(disc_lo[i], disc_hi[i])
                    self._discovered_fps[prop.name] = fp
                    if self.track_paths:
                        self._discoveries[prop.name] = self._reconstruct(fp)

            if self._all_discovered():
                break
            if target_states is not None and self._unique_states >= target_states:
                break
            if new_count == 0:
                break
            if new_count > F:
                raise RuntimeError(
                    f"frontier overflow: wave produced {new_count} > {F} "
                    "states; re-run with a larger frontier_capacity"
                )

            frontier = out["frontier"]
            fval = out["fval"]
            ebits = out["ebits"]
            depth += 1

            if reporter is not None:
                reporter.report_checking(
                    ReportData(
                        total_states=self._total_states,
                        unique_states=self._unique_states,
                        max_depth=self._max_depth,
                        duration_sec=self.duration_sec(),
                        done=False,
                    )
                )

    # -- reconstruction ---------------------------------------------------

    def _vec_fp(self, row: np.ndarray) -> int:
        lo, hi = fingerprint_u32v(row.reshape(1, -1), np)
        return _fp_int(lo[0], hi[0])

    def _reconstruct(self, fp: int) -> Path:
        """Walk the parent forest, then replay the HOST model matching
        device fingerprints of encoded successors (bfs.rs:371-400 +
        path.rs:20-97, with the encoder as the bridge)."""
        if not self.track_paths:
            raise RuntimeError(
                "path reconstruction requires track_paths=True"
            )
        fps = [fp]
        while True:
            parent = self.generated.get(fps[-1])
            if parent is None:
                break
            fps.append(parent)
        fps.reverse()

        model = self.model
        enc = self.encoded
        state = None
        for init_state in model.init_states():
            if self._vec_fp(np.asarray(enc.encode(init_state), np.uint32)) == fps[0]:
                state = init_state
                break
        if state is None:
            raise RuntimeError(
                f"no init state encodes to fingerprint {fps[0]:#x}; "
                "encode()/init_vecs() disagree"
            )
        steps = []
        for next_fp in fps[1:]:
            found = False
            for action in model.actions(state):
                next_state = model.next_state(state, action)
                if next_state is None:
                    continue
                vec = np.asarray(enc.encode(next_state), np.uint32)
                if self._vec_fp(vec) == next_fp:
                    steps.append((state, action))
                    state = next_state
                    found = True
                    break
            if not found:
                raise RuntimeError(
                    f"no host successor encodes to {next_fp:#x}: the "
                    "vectorized step_vec disagrees with the host model"
                )
        steps.append((state, None))
        return Path(steps)
