"""On-demand checker: expands states only when asked.

Re-implements the semantics of stateright src/checker/on_demand.rs:
a BFS variant whose frontier sits idle until the Explorer requests a
specific fingerprint (``check_fingerprint``, on_demand.rs:139-159) or
flips it into exhaustive mode (``run_to_completion``,
on_demand.rs:160-165). The reference parks worker threads on a control
channel; here the same contract is a synchronous incremental engine —
requests expand immediately, which is equivalent observable behavior
for the Explorer's HTTP API.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

import time

from ..checker import Checker, CheckerBuilder
from ..model import Expectation
from ..fingerprint import fingerprint
from ..path import Path
from ..report import Reporter
from .common import ParentTraceMixin, symmetry_refusal


class OnDemandChecker(ParentTraceMixin, Checker):
    def __init__(self, builder: CheckerBuilder):
        super().__init__(builder)
        if builder._symmetry is not None:
            raise symmetry_refusal("spawn_on_demand")
        self.generated: dict[int, Optional[int]] = {}
        #: fp -> (state, ebits, depth), awaiting expansion.
        self.pending: dict[int, tuple[object, int, int]] = {}
        self._order: deque[int] = deque()
        self._exhaustive = False
        self._seed_init()

    def _seed_init(self) -> None:
        ebits_init = self._eventually_bits_init()
        for init in self.model.init_states():
            if not self.model.within_boundary(init):
                continue
            fp = fingerprint(init)
            self._total_states += 1
            if fp not in self.generated:
                self.generated[fp] = None
                self.pending[fp] = (init, ebits_init, 1)
                self._order.append(fp)
        self._unique_states = len(self.generated)

    # -- Checker overrides: accessors reflect current progress ----------

    def _ensure_run(self, reporter: Optional[Reporter] = None) -> None:
        if self._exhaustive:
            self.run_to_completion()

    def is_done(self) -> bool:
        return not self.pending

    def join(self) -> "Checker":
        self.run_to_completion()
        return self

    # -- on-demand control (on_demand.rs:133-175, 403-412) ---------------

    def check_fingerprint(self, fp: int) -> None:
        """Expand the pending state with digest ``fp``, if any."""
        job = self.pending.pop(fp, None)
        if job is not None:
            state, ebits, depth = job
            self._expand(state, fp, ebits, depth)

    def run_to_completion(self) -> None:
        """Switch to exhaustive BFS (on_demand.rs:160-165).

        This engine bypasses the base ``_ensure_run`` (its accessors
        reflect incremental progress), so the round-14 trace bracket
        lives here: a tracer-active exhaustive pass opens its own
        run, and a pass that DRAINS the space sweeps exhaustion
        verdicts like every other engine — properties discovered
        earlier by Explorer browsing carry their (real-time) verdict
        events from the browse, outside any run."""
        from .. import telemetry

        self._exhaustive = True
        if self._done and not self._order:
            # already drained: accessors re-enter here via the
            # _ensure_run override — nothing to explore, and
            # re-opening a trace run would duplicate the verdicts
            return
        if self._started_at is None:
            self._started_at = time.monotonic()
        tracer = telemetry.current_tracer()
        if tracer is not None and not tracer._run_open:
            tracer.begin_run(lane=self._lane_config())
        else:
            tracer = None  # an enclosing run owns the bracket
        target_states = self.builder._target_state_count
        try:
            while self._order:
                fp = self._order.popleft()
                job = self.pending.pop(fp, None)
                if job is None:
                    continue  # already expanded via check_fingerprint
                state, ebits, depth = job
                self._expand(state, fp, ebits, depth)
                if self._all_discovered():
                    break
                if target_states is not None and self._unique_states >= target_states:
                    break
        except Exception as exc:
            # close the bracket on a model panic (the base
            # _ensure_run's contract): an unterminated run would
            # swallow every later event into a dead run view
            self._finished_at = time.monotonic()
            if tracer is not None:
                tracer.end_run(
                    error=f"{type(exc).__name__}: {exc}",
                    **self._run_stats(),
                )
            raise
        self._finished_at = time.monotonic()
        self._done = not self.pending
        if tracer is not None:
            if self._done:
                self._emit_settlement_verdicts(tracer)
            tracer.end_run(error=None, **self._run_stats())

    # -- shared expansion (mirrors bfs.rs check_block) -------------------

    def _expand(self, state, fp: int, ebits: int, depth: int) -> None:
        model = self.model
        props = list(model.properties())
        self._max_depth = max(self._max_depth, depth)

        visitor = self.builder._visitor
        if visitor is not None:
            visitor.visit(
                model, Path.from_fingerprints(model, self._reconstruct_fps(fp))
            )

        for i, prop in enumerate(props):
            if prop.expectation == Expectation.ALWAYS:
                if not prop.condition(model, state):
                    self._discover(prop.name, fp, depth=depth)
            elif prop.expectation == Expectation.SOMETIMES:
                if prop.condition(model, state):
                    self._discover(prop.name, fp, depth=depth)
            else:
                if ebits & (1 << i) and prop.condition(model, state):
                    ebits &= ~(1 << i)

        target_depth = self.builder._target_max_depth
        if target_depth is not None and depth >= target_depth:
            return

        is_terminal = True
        for action in model.actions(state):
            next_state = model.next_state(state, action)
            if next_state is None:
                continue
            if not model.within_boundary(next_state):
                continue
            is_terminal = False
            next_fp = fingerprint(next_state)
            self._total_states += 1
            if next_fp not in self.generated:
                self.generated[next_fp] = fp
                self._unique_states += 1
                self.pending[next_fp] = (next_state, ebits, depth + 1)
                self._order.append(next_fp)

        if is_terminal and ebits:
            for i, prop in enumerate(props):
                if ebits & (1 << i):
                    self._discover(prop.name, fp, depth=depth)
