"""The hybrid racer: host DFS vs the device wave engine, first done wins.

The measured TTFC profile (PERF.md, BENCH ttfc lane) is bimodal:
shallow bugs favor the host (increment lost-update: host DFS 2ms vs
the device engine's ~70ms per-dispatch floor), deep verification
favors the device by ~83x (paxos 2c full check: 16.5s vs 0.2s). A
tool that loses on the easy half invites the wrong engine choice, so
``spawn_hybrid()`` runs BOTH — the host depth-first search in a
daemon thread, the device sort-merge engine in the calling thread —
and adopts whichever completes first, cancelling the loser at its
next check point (per DFS pop / per device chunk readback).

This is the single-machine analog of the reference racing its
checker threads for discovery identity (bfs.rs records whichever
thread's discovery lands first): here whole ENGINES race, and the
winner's complete result surface (counts, discoveries, paths) is
adopted wholesale — both sides share the fingerprint/path plumbing,
so discoveries replay identically either way.

The host thread runs pure Python and the device thread spends its
time inside XLA dispatch (GIL released), so the race costs neither
side more than normal thread timeslicing.

Cold-cache caveat: the device program build is not interruptible, so
the very first run of a configuration is bounded below by the XLA
compile even when the host wins in milliseconds (the device side
checks the cancel flag before the build and per chunk after it; the
persistent compile cache makes every later run race at true speed).
"""

from __future__ import annotations

import threading
from typing import Optional

from ..checker import Checker, CheckerBuilder
from ..report import Reporter
from .dfs import DfsChecker


class HybridChecker(Checker):
    """``CheckerBuilder.spawn_hybrid()``.

    ``device_kwargs`` go to :meth:`spawn_tpu_sortmerge` (``encoded``,
    capacities, ``sparse``, ...). After ``join()``, :attr:`winner` is
    ``"host"`` or ``"device"`` and every ``Checker`` accessor reflects
    the winning engine's run.
    """

    def __init__(self, builder: CheckerBuilder, **device_kwargs):
        super().__init__(builder)
        self._device_kwargs = device_kwargs
        #: which engine completed first ("host" | "device")
        self.winner: Optional[str] = None
        #: staged snapshot path (:meth:`resume_from`): the DEVICE side
        #: resumes from it — a host DFS has no snapshot to restore, so
        #: a resumed race is "resumed device vs fresh host", which
        #: preserves the racer's contract (both sides explore the same
        #: space to the same answers).
        self._resume_path = None
        self._resume_kw: dict = {}

    def resume_from(self, path: str, **kw) -> None:
        """Stage a device-engine snapshot for the next run (see
        checkpoint.resume_from; validation happens at run time, on
        the device checker the race constructs)."""
        self._resume_path = path
        self._resume_kw = kw

    def _run(self, reporter: Optional[Reporter] = None) -> None:
        from .tpu_sortmerge import SortMergeTpuBfsChecker

        host = DfsChecker(self.builder)
        device = SortMergeTpuBfsChecker(
            self.builder, **self._device_kwargs
        )
        if self._resume_path is not None:
            device.resume_from(self._resume_path, **self._resume_kw)
        stop_host = threading.Event()
        stop_device = threading.Event()
        host.cancel_event = stop_host
        device.cancel_event = stop_device
        lock = threading.Lock()
        host_error: list = []

        def claim(name: str) -> bool:
            with lock:
                if self.winner is None:
                    self.winner = name
                    return True
                return False

        def run_host():
            try:
                host._ensure_run()
            except Exception as exc:  # surfaced if the host wins
                host_error.append(exc)
                return
            if not host.cancelled and claim("host"):
                stop_device.set()

        t = threading.Thread(target=run_host, daemon=True)
        t.start()
        device_error = None
        try:
            try:
                device._ensure_run(reporter)
            except Exception as exc:
                device_error = exc
            if device_error is None and not device.cancelled and claim(
                "device"
            ):
                stop_host.set()
            t.join()
        finally:
            # The loser must be cancelled AND joined on EVERY exit
            # path — including a BaseException out of the device side
            # (KeyboardInterrupt, a supervisor-exhausted injected
            # fault). A stale host thread that outlives _run keeps
            # emitting telemetry into whatever run opens next (a
            # RESUMED run's trace would interleave a dead race's
            # events), and its eventual completion could race the
            # winner bookkeeping. The join is safe here: stop_host is
            # set, and the host checks its cancel event per DFS pop.
            if t.is_alive():
                stop_host.set()
                t.join()
        if self.winner is None:
            # Both failed (or the device failed and the host errored) —
            # a side only claims after completing without an exception.
            raise device_error or host_error[0]
        if host_error and self.winner == "device":
            if isinstance(host_error[0], MemoryError):
                # Host-side resource exhaustion, not a model error: the
                # host DFS holds O(states × depth) trace tuples — on
                # deep workloads (exactly where the device wins by
                # ~83x) running out of host memory is the race being
                # LOST, not a defect in the model. Keep the device's
                # completed verification; note the host's demise — as
                # a warning for humans AND a structured telemetry
                # event (phase + message) so a traced run records the
                # race outcome in the artifact, not only on stderr
                # (the memory-observability contract: host OOM is a
                # memory datum).
                import warnings

                from .. import telemetry

                msg = (
                    "hybrid race: host engine ran out of memory; "
                    "adopting the device engine's completed result"
                )
                telemetry.emit(
                    "hybrid_host_oom",
                    phase="host_dfs",
                    message=msg,
                    winner=self.winner,
                    error=f"{type(host_error[0]).__name__}: "
                          f"{host_error[0]}",
                )
                warnings.warn(
                    msg,
                    RuntimeWarning,
                    stacklevel=2,
                )
            else:
                # The device won but the host engine RAISED (not lost
                # the race): a genuine model error — e.g. a panicking
                # handler, examples/panic.rs semantics — can manifest
                # only on the host, because hand encodings never run
                # the host model's enumeration. The reference
                # propagates worker panics (checker.rs joins its
                # threads); racing past one would report a clean
                # verification for a panicking model, so surface it
                # instead of adopting the device result.
                raise RuntimeError(
                    "hybrid race: the device engine completed but the "
                    "host engine raised a model error (not a "
                    "cancellation) — refusing to mask it"
                ) from host_error[0]
        win = host if self.winner == "host" else device
        # Adopt the winner's result surface wholesale.
        self._winner_checker = win
        self._discoveries = win._discoveries
        self._total_states = win._total_states
        self._unique_states = win._unique_states
        self._max_depth = win._max_depth

    def discovered_property_names(self) -> set:
        self._ensure_run()
        w = self._winner_checker
        if hasattr(w, "discovered_property_names"):
            return w.discovered_property_names()
        return set(w._discoveries)

    def discoveries(self):
        self._ensure_run()
        return self._winner_checker.discoveries()

    def assert_properties(self) -> None:
        self._ensure_run()
        self._winner_checker.assert_properties()
