"""Host breadth-first checker — the host correctness oracle.

Re-implements the semantics of the reference's parallel BFS
(stateright src/checker/bfs.rs): FIFO frontier, fingerprint-keyed
visited map storing child→parent digests for path reconstruction
(bfs.rs:28-29, 371-400), per-path ``EventuallyBits`` with the documented
revisit false-negative (bfs.rs:285-303), terminal-state eventually
counterexamples (bfs.rs:317-324), and early exit once every property
has a discovery or the state target is reached (bfs.rs:128-145).

``CheckerBuilder.threads(n)`` spawns n worker threads over a shared
pending deque in blocks of 1,500 states — the reference's work-share
granularity (bfs.rs:124, job_market.rs:66-147). The model callbacks
(actions/next_state/properties) run outside the lock; dedup, counter
updates, and discovery recording apply under it, so counts are exact
and the discovered property SET matches the sequential run (which
state discovers a property first can differ between runs — the same
race the reference's worker threads have). CPython's GIL means the
speedup is real only where the model's callbacks release it (C-backed
hashing, numpy); on pure-Python models threads(n) is parity, not
speed — the vectorized TPU engines are this framework's parallelism
story (:mod:`stateright_tpu.checkers.tpu`).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from ..checker import Checker, CheckerBuilder
from ..model import Expectation
from ..fingerprint import fingerprint
from ..path import Path
from ..report import ReportData, Reporter
from .common import ParentTraceMixin, symmetry_refusal

#: states handed to a worker per lock acquisition (bfs.rs:124).
JOB_BLOCK = 1500


class BfsChecker(ParentTraceMixin, Checker):
    def __init__(self, builder: CheckerBuilder):
        super().__init__(builder)
        if builder._symmetry is not None:
            raise symmetry_refusal("spawn_bfs")
        #: child fingerprint -> parent fingerprint (None for init states);
        #: the complete parent-pointer forest (bfs.rs:28-29).
        self.generated: dict[int, Optional[int]] = {}

    def _run(self, reporter: Optional[Reporter] = None) -> None:
        from .. import telemetry

        model = self.model
        props = list(model.properties())
        ebits_init = self._eventually_bits_init()
        visitor = self.builder._visitor
        target_states = self.builder._target_state_count
        target_depth = self.builder._target_max_depth
        # Host-phase telemetry: property evaluation runs once per
        # popped state, so it accumulates into ONE phase_total event
        # instead of an event per state (telemetry.phase_acc); the
        # shared no-op keeps the untraced loop cost-free.
        tracer = telemetry.current_tracer()
        prop_acc = (tracer.phase_acc("property_check") if tracer
                    else telemetry._NULL_SPAN)

        pending: deque[tuple[object, int, int, int]] = deque()
        for init in model.init_states():
            if not model.within_boundary(init):
                continue
            fp = fingerprint(init)
            self._total_states += 1
            if fp not in self.generated:
                self.generated[fp] = None
                pending.append((init, fp, ebits_init, 1))
        self._unique_states = len(self.generated)

        if self.builder._threads > 1:
            self._run_parallel(pending, reporter)
            return

        last_report = time.monotonic()
        while pending:
            state, fp, ebits, depth = pending.popleft()
            self._max_depth = max(self._max_depth, depth)

            if visitor is not None:
                visitor.visit(
                    model, Path.from_fingerprints(model, self._reconstruct_fps(fp))
                )

            # Property evaluation on the popped state (bfs.rs:223-268).
            # Discoveries are RECORDED after the timed block: _discover
            # reconstructs the counterexample path under its own span,
            # which must not also count into property_check.
            hit = []
            with prop_acc:
                for i, prop in enumerate(props):
                    if prop.expectation == Expectation.ALWAYS:
                        if not prop.condition(model, state):
                            hit.append(prop.name)
                    elif prop.expectation == Expectation.SOMETIMES:
                        if prop.condition(model, state):
                            hit.append(prop.name)
                    else:  # EVENTUALLY
                        if ebits & (1 << i) and prop.condition(model, state):
                            ebits &= ~(1 << i)
            for name in hit:
                self._discover(name, fp, depth=depth)

            if self._all_discovered():
                break
            if target_states is not None and self._unique_states >= target_states:
                break

            # Depth bound: do not expand further (bfs.rs:210-215); a
            # depth-cut state is not "terminal" for eventually purposes.
            if target_depth is not None and depth >= target_depth:
                continue

            # Expansion (bfs.rs:275-316).
            is_terminal = True
            for action in model.actions(state):
                next_state = model.next_state(state, action)
                if next_state is None:
                    continue
                if not model.within_boundary(next_state):
                    continue
                is_terminal = False
                next_fp = fingerprint(next_state)
                self._total_states += 1
                if next_fp not in self.generated:
                    self.generated[next_fp] = fp
                    self._unique_states += 1
                    pending.append((next_state, next_fp, ebits, depth + 1))
                # else: ebits dropped on revisit — reproduces the
                # documented false negative (bfs.rs:285-303).

            # Terminal state: surviving eventually-bits are
            # counterexamples (bfs.rs:317-324).
            if is_terminal and ebits:
                for i, prop in enumerate(props):
                    if ebits & (1 << i):
                        self._discover(prop.name, fp, depth=depth)

            if reporter is not None:
                now = time.monotonic()
                if now - last_report >= reporter.delay():
                    last_report = now
                    reporter.report_checking(
                        ReportData(
                            total_states=self._total_states,
                            unique_states=self._unique_states,
                            max_depth=self._max_depth,
                            duration_sec=self.duration_sec(),
                            done=False,
                        )
                    )

    # -- worker-pool variant (threads(n), bfs.rs + job_market.rs) --------

    def _run_parallel(
        self, pending: deque, reporter: Optional[Reporter]
    ) -> None:
        """N workers over the shared pending deque in JOB_BLOCK
        chunks: model callbacks run outside the lock, dedup /
        counters / discovery recording under it. Early exit
        (all-discovered, target_state_count) is approximate by up to
        the blocks in flight — the same slack the reference's
        work-sharing has (checker.rs "approximately", bfs.rs:128-145).
        """
        model = self.model
        props = list(model.properties())
        target_states = self.builder._target_state_count
        target_depth = self.builder._target_max_depth
        visitor = self.builder._visitor

        cv = threading.Condition()
        run = {"active": 0, "stop": False}
        errors: list = []

        def evaluate(job):
            """One job's model callbacks; touches NO shared state
            (reads of self.generated for the visitor are safe: CPython
            dict reads are atomic under the GIL and parents of a
            popped state are never re-written)."""
            state, fp, ebits, depth = job
            discovered = []
            for i, prop in enumerate(props):
                if prop.expectation == Expectation.ALWAYS:
                    if not prop.condition(model, state):
                        discovered.append(prop.name)
                elif prop.expectation == Expectation.SOMETIMES:
                    if prop.condition(model, state):
                        discovered.append(prop.name)
                else:  # EVENTUALLY
                    if ebits & (1 << i) and prop.condition(model, state):
                        ebits &= ~(1 << i)
            if visitor is not None:
                visitor.visit(
                    model,
                    Path.from_fingerprints(
                        model, self._reconstruct_fps(fp)
                    ),
                )
            succs = []
            is_terminal = True
            if target_depth is None or depth < target_depth:
                for action in model.actions(state):
                    next_state = model.next_state(state, action)
                    if next_state is None:
                        continue
                    if not model.within_boundary(next_state):
                        continue
                    is_terminal = False
                    succs.append((next_state, fingerprint(next_state)))
            else:
                is_terminal = False  # depth-cut, not terminal
            term_evt = (
                [
                    prop.name
                    for i, prop in enumerate(props)
                    if ebits & (1 << i)
                ]
                if is_terminal and ebits
                else []
            )
            return fp, ebits, depth, discovered, succs, term_evt

        def worker():
            while True:
                with cv:
                    while (
                        not pending
                        and run["active"] > 0
                        and not run["stop"]
                    ):
                        cv.wait(0.05)
                    if run["stop"] or (
                        not pending and run["active"] == 0
                    ):
                        cv.notify_all()
                        return
                    block = [
                        pending.popleft()
                        for _ in range(min(JOB_BLOCK, len(pending)))
                    ]
                    run["active"] += 1
                try:
                    results = [evaluate(j) for j in block]
                except Exception as exc:  # propagate model panics
                    with cv:
                        errors.append(exc)
                        run["stop"] = True
                        run["active"] -= 1
                        cv.notify_all()
                    return
                with cv:
                    for fp, ebits, depth, disc, succs, term in results:
                        self._max_depth = max(self._max_depth, depth)
                        for name in disc:
                            self._discover(name, fp, depth=depth)
                        for next_state, next_fp in succs:
                            self._total_states += 1
                            if next_fp not in self.generated:
                                self.generated[next_fp] = fp
                                self._unique_states += 1
                                pending.append(
                                    (next_state, next_fp, ebits,
                                     depth + 1)
                                )
                        for name in term:
                            self._discover(name, fp, depth=depth)
                    if self._all_discovered() or (
                        target_states is not None
                        and self._unique_states >= target_states
                    ):
                        run["stop"] = True
                    run["active"] -= 1
                    cv.notify_all()

        workers = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(self.builder._threads)
        ]
        for t in workers:
            t.start()
        delay = reporter.delay() if reporter is not None else 0.5
        while any(t.is_alive() for t in workers):
            # One deadline per report cycle (joining every worker with
            # the full delay would stretch the cadence to
            # n_threads × delay).
            deadline = time.monotonic() + max(delay, 0.05)
            for t in workers:
                t.join(timeout=max(deadline - time.monotonic(), 0.01))
            if reporter is not None and any(
                t.is_alive() for t in workers
            ):
                with cv:
                    data = ReportData(
                        total_states=self._total_states,
                        unique_states=self._unique_states,
                        max_depth=self._max_depth,
                        duration_sec=self.duration_sec(),
                        done=False,
                    )
                reporter.report_checking(data)
        if errors:
            raise errors[0]
