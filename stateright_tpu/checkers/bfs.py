"""Host breadth-first checker — the sequential correctness oracle.

Re-implements the semantics of the reference's parallel BFS
(stateright src/checker/bfs.rs): FIFO frontier, fingerprint-keyed
visited map storing child→parent digests for path reconstruction
(bfs.rs:28-29, 371-400), per-path ``EventuallyBits`` with the documented
revisit false-negative (bfs.rs:285-303), terminal-state eventually
counterexamples (bfs.rs:317-324), and early exit once every property
has a discovery or the state target is reached (bfs.rs:128-145).

Where the reference gets parallelism from worker threads + a
work-stealing job market, this host engine is deliberately sequential:
it exists to define ground truth for the vectorized TPU engine
(:mod:`stateright_tpu.checkers.tpu`), which runs the same wave
semantics as device kernels.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

from ..checker import Checker, CheckerBuilder
from ..model import Expectation
from ..fingerprint import fingerprint
from ..path import Path
from ..report import ReportData, Reporter
from .common import ParentTraceMixin


class BfsChecker(ParentTraceMixin, Checker):
    def __init__(self, builder: CheckerBuilder):
        super().__init__(builder)
        if builder._symmetry is not None:
            raise ValueError(
                "symmetry reduction requires spawn_dfs or spawn_simulation "
                "(as in the reference: dfs.rs:300-311, simulation.rs:252-256)"
            )
        #: child fingerprint -> parent fingerprint (None for init states);
        #: the complete parent-pointer forest (bfs.rs:28-29).
        self.generated: dict[int, Optional[int]] = {}

    def _run(self, reporter: Optional[Reporter] = None) -> None:
        model = self.model
        props = list(model.properties())
        ebits_init = self._eventually_bits_init()
        visitor = self.builder._visitor
        target_states = self.builder._target_state_count
        target_depth = self.builder._target_max_depth

        pending: deque[tuple[object, int, int, int]] = deque()
        for init in model.init_states():
            if not model.within_boundary(init):
                continue
            fp = fingerprint(init)
            self._total_states += 1
            if fp not in self.generated:
                self.generated[fp] = None
                pending.append((init, fp, ebits_init, 1))
        self._unique_states = len(self.generated)

        last_report = time.monotonic()
        while pending:
            state, fp, ebits, depth = pending.popleft()
            self._max_depth = max(self._max_depth, depth)

            if visitor is not None:
                visitor.visit(
                    model, Path.from_fingerprints(model, self._reconstruct_fps(fp))
                )

            # Property evaluation on the popped state (bfs.rs:223-268).
            for i, prop in enumerate(props):
                if prop.expectation == Expectation.ALWAYS:
                    if not prop.condition(model, state):
                        self._discover(prop.name, fp)
                elif prop.expectation == Expectation.SOMETIMES:
                    if prop.condition(model, state):
                        self._discover(prop.name, fp)
                else:  # EVENTUALLY
                    if ebits & (1 << i) and prop.condition(model, state):
                        ebits &= ~(1 << i)

            if self._all_discovered():
                break
            if target_states is not None and self._unique_states >= target_states:
                break

            # Depth bound: do not expand further (bfs.rs:210-215); a
            # depth-cut state is not "terminal" for eventually purposes.
            if target_depth is not None and depth >= target_depth:
                continue

            # Expansion (bfs.rs:275-316).
            is_terminal = True
            for action in model.actions(state):
                next_state = model.next_state(state, action)
                if next_state is None:
                    continue
                if not model.within_boundary(next_state):
                    continue
                is_terminal = False
                next_fp = fingerprint(next_state)
                self._total_states += 1
                if next_fp not in self.generated:
                    self.generated[next_fp] = fp
                    self._unique_states += 1
                    pending.append((next_state, next_fp, ebits, depth + 1))
                # else: ebits dropped on revisit — reproduces the
                # documented false negative (bfs.rs:285-303).

            # Terminal state: surviving eventually-bits are
            # counterexamples (bfs.rs:317-324).
            if is_terminal and ebits:
                for i, prop in enumerate(props):
                    if ebits & (1 << i):
                        self._discover(prop.name, fp)

            if reporter is not None:
                now = time.monotonic()
                if now - last_report >= reporter.delay():
                    last_report = now
                    reporter.report_checking(
                        ReportData(
                            total_states=self._total_states,
                            unique_states=self._unique_states,
                            max_depth=self._max_depth,
                            duration_sec=self.duration_sec(),
                            done=False,
                        )
                    )
