"""Checker engines: host BFS/DFS/simulation/on-demand + the TPU wave engine."""
