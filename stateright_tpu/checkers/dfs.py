"""Host depth-first checker.

Re-implements the reference DFS (stateright src/checker/dfs.rs):
LIFO stack, visited *set* of fingerprints (no parent pointers,
dfs.rs:27), each job carrying its full fingerprint trace for discovery
reconstruction (dfs.rs:30), and the symmetry-reduction hook — insert
``fingerprint(representative(state))`` into the visited set while
continuing the path with the *original* state (dfs.rs:300-311; the
rationale is pinned by the reference's own regression test,
dfs.rs:484-510: paths must stay replayable).
"""

from __future__ import annotations

import time
from typing import Optional

from ..checker import Checker, CheckerBuilder
from ..model import Expectation
from ..fingerprint import fingerprint
from ..path import Path
from ..report import ReportData, Reporter


class DfsChecker(Checker):
    def __init__(self, builder: CheckerBuilder):
        super().__init__(builder)
        self.visited: set[int] = set()
        #: optional threading.Event: when set, _run returns early with
        #: partial results and ``cancelled`` True (the hybrid racer's
        #: losing side; see checkers/hybrid.py).
        self.cancel_event = None
        self.cancelled = False

    def _discover(self, name: str, trace: tuple[int, ...]) -> None:
        if name not in self._discoveries:
            from .. import telemetry

            # verdict before reconstruction (round 14): the settle
            # moment, not the path-materialization moment
            prop = self.model.property_by_name(name)
            telemetry.emit(
                "verdict", property=name,
                expectation=prop.expectation.name.lower(),
                kind="discovery", wave=None, depth=len(trace),
            )
            with telemetry.span("counterexample_reconstruction",
                                property=name):
                self._discoveries[name] = Path.from_fingerprints(
                    self.model, list(trace)
                )

    def _run(self, reporter: Optional[Reporter] = None) -> None:
        from .. import telemetry

        model = self.model
        props = list(model.properties())
        ebits_init = self._eventually_bits_init()
        visitor = self.builder._visitor
        symmetry = self.builder._symmetry
        target_states = self.builder._target_state_count
        target_depth = self.builder._target_max_depth
        # Host-phase telemetry: per-state costs accumulate into one
        # phase_total event apiece (telemetry.phase_acc); the shared
        # no-op keeps the untraced loop cost-free.
        tracer = telemetry.current_tracer()
        prop_acc = (tracer.phase_acc("property_check") if tracer
                    else telemetry._NULL_SPAN)
        sym_acc = (
            tracer.phase_acc("symmetry_canonicalization")
            if tracer is not None and symmetry is not None
            else telemetry._NULL_SPAN
        )

        def visited_key(state, fp: int) -> int:
            if symmetry is None:
                return fp
            with sym_acc:
                return fingerprint(symmetry(state))

        pending: list[tuple[object, tuple[int, ...], int]] = []
        for init in model.init_states():
            if not model.within_boundary(init):
                continue
            fp = fingerprint(init)
            self._total_states += 1
            key = visited_key(init, fp)
            if key not in self.visited:
                self.visited.add(key)
                pending.append((init, (fp,), ebits_init))
        self._unique_states = len(self.visited)

        last_report = time.monotonic()
        cancel = self.cancel_event
        while pending:
            if cancel is not None and cancel.is_set():
                self.cancelled = True
                return
            state, trace, ebits = pending.pop()
            depth = len(trace)
            self._max_depth = max(self._max_depth, depth)

            if visitor is not None:
                visitor.visit(model, Path.from_fingerprints(model, list(trace)))

            # Discoveries are RECORDED after the timed block: _discover
            # reconstructs the counterexample path under its own span,
            # which must not also count into property_check.
            hit = []
            with prop_acc:
                for i, prop in enumerate(props):
                    if prop.expectation == Expectation.ALWAYS:
                        if not prop.condition(model, state):
                            hit.append(prop.name)
                    elif prop.expectation == Expectation.SOMETIMES:
                        if prop.condition(model, state):
                            hit.append(prop.name)
                    else:  # EVENTUALLY
                        if ebits & (1 << i) and prop.condition(model, state):
                            ebits &= ~(1 << i)
            for name in hit:
                self._discover(name, trace)

            if self._all_discovered():
                break
            if target_states is not None and self._unique_states >= target_states:
                break
            if target_depth is not None and depth >= target_depth:
                continue

            is_terminal = True
            for action in model.actions(state):
                next_state = model.next_state(state, action)
                if next_state is None:
                    continue
                if not model.within_boundary(next_state):
                    continue
                is_terminal = False
                next_fp = fingerprint(next_state)
                self._total_states += 1
                key = visited_key(next_state, next_fp)
                if key not in self.visited:
                    self.visited.add(key)
                    self._unique_states += 1
                    pending.append((next_state, trace + (next_fp,), ebits))

            if is_terminal and ebits:
                for i, prop in enumerate(props):
                    if ebits & (1 << i):
                        self._discover(prop.name, trace)

            if reporter is not None:
                now = time.monotonic()
                if now - last_report >= reporter.delay():
                    last_report = now
                    reporter.report_checking(
                        ReportData(
                            total_states=self._total_states,
                            unique_states=self._unique_states,
                            max_depth=self._max_depth,
                            duration_sec=self.duration_sec(),
                            done=False,
                        )
                    )
