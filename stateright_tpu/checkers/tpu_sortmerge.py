"""The sort-merge wave engine: dedup without scatters.

TPU microbenchmarks (v5e, this repo's stage ablation) show the hash
table engine's cost profile is inverted on TPU hardware: arbitrary-
index scatter/gather — the heart of GPU-style open-addressing
(ops/hashset.py) — runs at ~2M rows per 100ms, while ``lax.sort``
moves 2M 2-lane rows in 1.8ms. XLA:TPU lowers scatters to serialized
updates; sorts are native and fast. So this engine re-architects the
wave around sorts, the classic vector-machine model-checking layout:

* The visited set is a **sorted fingerprint array** (two uint32 limb
  lanes, all-ones sentinel padding), not a hash table.
* Per wave: fingerprint all padded candidates (elementwise) →
  **sort#1** ``(hi, lo, row)`` compacts valid candidates to the B
  lowest keys (invalid rows carry sentinel keys and sort last) → one
  B-row payload gather → **sort#2** merges candidate keys with the
  visited array (stable, visited first, so first-of-run marks the
  winner and intra-wave duplicates resolve for free) → **sort#3**
  rebuilds the deduplicated visited array (losers sentinelized, slice
  back to capacity) → **sort#4** compacts the new states' positions
  for the next frontier, followed by small F-row gathers.
* The parent forest is an **append-only device log** of
  (child, parent) fingerprint pairs written with
  ``dynamic_update_slice`` — contiguous writes, no scatter — drained
  lazily on the host only when a counterexample path is reconstructed.

Everything else — the device-resident multi-wave ``lax.while_loop``,
packed-stats chunk sync, properties/EventuallyBits/discovery logic —
is shared with :mod:`stateright_tpu.checkers.tpu`.

Measured (2pc rm=7, 296,448 states, warm, one v5e chip): the hash
table engine runs ~390ms/wave; this engine's stage budget is ~20ms/wave
(see bench.py for recorded end-to-end numbers).
"""

from __future__ import annotations

import numpy as np

from ..model import Expectation
from ..ops.fingerprint import fingerprint_u32v
from ..ops.u64 import U64, u64_add
from .tpu import (
    TpuBfsChecker,
    discovery_update,
    expand_frontier,
)

_SENT = 0xFFFFFFFF


class SortMergeTpuBfsChecker(TpuBfsChecker):
    """``CheckerBuilder.spawn_tpu_sortmerge()``.

    ``capacity`` is the visited-array length — unlike the hash table
    there is no load-factor pressure: it can sit at exactly the
    expected unique-state count (overflow is detected, not silent).

    ``tiles`` splits the frontier into that many expansion tiles
    processed sequentially inside each wave: peak memory for the flat
    successor tensor drops from ``F*K*W`` to ``(F/tiles)*K*W`` lanes,
    which is what lets 10⁷-10⁸-state spaces (2pc rm=9/10) fit on one
    chip. The candidate budget is per-tile: each tile may contribute at
    most ``cand_capacity/tiles`` valid successors (overflow detected).
    """

    def __init__(self, builder, tiles: int = 1, **kwargs):
        super().__init__(builder, **kwargs)
        self.tiles = tiles
        if self.frontier_capacity % tiles:
            raise ValueError(
                f"frontier_capacity {self.frontier_capacity} not divisible "
                f"by tiles {tiles}"
            )

    def _cache_extras(self) -> tuple:
        return ("sortmerge", self.tiles)

    def _maybe_warn_occupancy(self, occupancy: float) -> None:
        """No probe pressure: the sorted array works at 100% occupancy
        and overflow is detected exactly — nothing to warn about."""

    def _cand_overflow_message(self) -> str:
        fk = self.frontier_capacity * self.encoded.max_actions
        per_tile = -(-min(self.cand_capacity or fk, fk) // self.tiles)
        return (
            f"candidate-buffer overflow: an expansion tile generated more "
            f"than {per_tile} valid successors "
            f"(cand_capacity/tiles = {per_tile}); re-run with a larger "
            "cand_capacity or fewer tiles"
        )

    # -- device programs ---------------------------------------------------

    def _build_programs(self, n0: int):
        import jax
        import jax.numpy as jnp
        from jax import lax

        enc = self.encoded
        props = list(self.model.properties())
        n_props = len(props)
        evt_idx = [
            i for i, p in enumerate(props)
            if p.expectation == Expectation.EVENTUALLY
        ]
        if evt_idx and max(evt_idx) >= 32:
            raise ValueError(
                "the TPU engine supports eventually properties only at "
                "property indices < 32; reorder properties() so eventually "
                f"properties come first (got index {max(evt_idx)})"
            )
        K, W, F = enc.max_actions, enc.width, self.frontier_capacity
        C = self.capacity
        B = min(self.cand_capacity or F * K, F * K)
        target_states = self.builder._target_state_count
        target_depth = self.builder._target_max_depth
        waves_per_sync = self.waves_per_sync
        ebits_init = self._eventually_bits_init()
        track_paths = self.track_paths
        # Parent log rows: every unique state (≤ C) gets one entry;
        # the F-row block write at a dynamic offset needs headroom.
        L = C + F if track_paths else 0

        def clamp_keys(lo, hi):
            # All-ones is the visited-array padding sentinel; nudge
            # real fingerprints off it (mirrors the NonZero convention
            # at the other end of the range, ops/fingerprint.py).
            both = (lo == jnp.uint32(_SENT)) & (hi == jnp.uint32(_SENT))
            return lo, jnp.where(both, jnp.uint32(_SENT - 1), hi)

        def seed(init_rows):
            lo0, hi0 = fingerprint_u32v(init_rows, jnp)
            lo0, hi0 = clamp_keys(lo0, hi0)
            # Visited array: init keys sorted, sentinel padding.
            v_hi = jnp.full(C, _SENT, jnp.uint32).at[:n0].set(hi0)
            v_lo = jnp.full(C, _SENT, jnp.uint32).at[:n0].set(lo0)
            v_hi, v_lo = lax.sort((v_hi, v_lo), num_keys=2)
            frontier = jnp.zeros((F, W), dtype=jnp.uint32).at[:n0].set(
                init_rows
            )
            fval = jnp.arange(F) < n0
            ebits = jnp.where(fval, jnp.uint32(ebits_init), jnp.uint32(0))
            return dict(
                v_lo=v_lo,
                v_hi=v_hi,
                pl_child_lo=jnp.zeros(L, jnp.uint32),
                pl_child_hi=jnp.zeros(L, jnp.uint32),
                pl_par_lo=jnp.zeros(L, jnp.uint32),
                pl_par_hi=jnp.zeros(L, jnp.uint32),
                pl_n=jnp.uint32(0),
                frontier=frontier,
                fval=fval,
                ebits=ebits,
                depth=jnp.int32(1),
                wchunk=jnp.int32(0),
                waves=jnp.uint32(0),
                gen_lo=jnp.uint32(n0),
                gen_hi=jnp.uint32(0),
                new=jnp.uint32(n0),
                disc_found=jnp.zeros(n_props, dtype=bool),
                disc_lo=jnp.zeros(n_props, dtype=jnp.uint32),
                disc_hi=jnp.zeros(n_props, dtype=jnp.uint32),
                overflow=jnp.bool_(n0 > C),
                f_overflow=jnp.bool_(False),
                c_overflow=jnp.bool_(False),
                done=jnp.bool_(n0 == 0),
            )

        NT = self.tiles
        T = F // NT
        # Round the per-tile budget up so the user's cand_capacity is a
        # floor, never silently truncated.
        Bt = -(-B // NT)
        B_eff = Bt * NT

        def body(c):
            if target_depth is None:
                expand = jnp.bool_(True)
            else:
                expand = c["depth"] < target_depth

            # Tiled expansion: each tile of T frontier rows expands,
            # fingerprints, and sort#1-compacts its own candidates into
            # a Bt-row segment of the shared candidate buffers
            # (contiguous dynamic_update_slice writes — no scatter).
            # Only the [T*K, W] tile tensor is ever materialized.
            def tile_body(t, acc):
                (
                    ck_lo, ck_hi, cst, cplo, cphi, ceb,
                    dfound, dlo, dhi, n_cand, c_overflow,
                ) = acc
                off = t * T
                tf = lax.dynamic_slice(c["frontier"], (off, 0), (T, W))
                tfv = lax.dynamic_slice(c["fval"], (off,), (T,))
                teb = lax.dynamic_slice(c["ebits"], (off,), (T,))
                ex = expand_frontier(
                    enc, props, evt_idx, tf, tfv, teb, expand
                )
                dfound, dlo, dhi = discovery_update(
                    props, ex, tfv, dfound, dlo, dhi
                )
                flat, valid = ex["flat"], ex["v"]
                k_lo, k_hi = fingerprint_u32v(flat, jnp)
                k_lo, k_hi = clamp_keys(k_lo, k_hi)
                k_lo = jnp.where(valid, k_lo, jnp.uint32(_SENT))
                k_hi = jnp.where(valid, k_hi, jnp.uint32(_SENT))
                t_cand = jnp.sum(valid)
                c_overflow = c_overflow | (t_cand > Bt)
                # Sort#1 (per tile): valid keys have the Bt lowest
                # values (invalid rows carry the sentinel key).
                rows = jnp.arange(T * K, dtype=jnp.uint32)
                s_hi, s_lo, s_row = lax.sort(
                    (k_hi, k_lo, rows), num_keys=2
                )
                s_hi, s_lo, s_row = s_hi[:Bt], s_lo[:Bt], s_row[:Bt]
                st = flat[s_row]
                prow = s_row // jnp.uint32(K)
                o = t * Bt
                ck_lo = lax.dynamic_update_slice(ck_lo, s_lo, (o,))
                ck_hi = lax.dynamic_update_slice(ck_hi, s_hi, (o,))
                cst = lax.dynamic_update_slice(cst, st, (o, 0))
                if track_paths:
                    # Parent fingerprints are only needed for the log.
                    cplo = lax.dynamic_update_slice(
                        cplo, ex["f_lo"][prow], (o,)
                    )
                    cphi = lax.dynamic_update_slice(
                        cphi, ex["f_hi"][prow], (o,)
                    )
                ceb = lax.dynamic_update_slice(
                    ceb, ex["ebits"][prow], (o,)
                )
                return (
                    ck_lo, ck_hi, cst, cplo, cphi, ceb,
                    dfound, dlo, dhi, n_cand + t_cand.astype(jnp.uint32),
                    c_overflow,
                )

            (
                s_lo, s_hi, b_state, b_par_lo, b_par_hi, b_ebits,
                disc_found, disc_lo, disc_hi, n_cand, c_overflow,
            ) = lax.fori_loop(
                0,
                NT,
                tile_body,
                (
                    jnp.full(B_eff, _SENT, jnp.uint32),
                    jnp.full(B_eff, _SENT, jnp.uint32),
                    jnp.zeros((B_eff, W), jnp.uint32),
                    jnp.zeros(B_eff if track_paths else 0, jnp.uint32),
                    jnp.zeros(B_eff if track_paths else 0, jnp.uint32),
                    jnp.zeros(B_eff, jnp.uint32),
                    c["disc_found"],
                    c["disc_lo"],
                    c["disc_hi"],
                    jnp.uint32(0),
                    c["c_overflow"],
                ),
            )

            # Sort#2: merge with the visited array. Stable sort with
            # the visited keys FIRST in the concatenation means the
            # first element of every equal-key run is the visited
            # entry when present — so is_new is first-of-run AND
            # from-candidates, and intra-wave duplicates resolve to
            # one winner for free.
            m_hi = jnp.concatenate([c["v_hi"], s_hi])
            m_lo = jnp.concatenate([c["v_lo"], s_lo])
            m_pos = jnp.concatenate(
                [
                    jnp.zeros(C, jnp.uint32),
                    jnp.arange(1, B_eff + 1, dtype=jnp.uint32),
                ]
            )
            m_hi, m_lo, m_pos = lax.sort((m_hi, m_lo, m_pos), num_keys=2)
            real = ~((m_hi == jnp.uint32(_SENT)) & (m_lo == jnp.uint32(_SENT)))
            prev_same = jnp.concatenate(
                [
                    jnp.zeros(1, bool),
                    (m_hi[1:] == m_hi[:-1]) & (m_lo[1:] == m_lo[:-1]),
                ]
            )
            is_new = real & ~prev_same & (m_pos > 0)
            new_count = jnp.sum(is_new)

            # Sort#3: rebuild the visited array — duplicate-run losers
            # become sentinels, then the C lowest keys are the new set.
            # Overflow iff a real key lands beyond capacity.
            u_hi = jnp.where(prev_same, jnp.uint32(_SENT), m_hi)
            u_lo = jnp.where(prev_same, jnp.uint32(_SENT), m_lo)
            u_hi, u_lo = lax.sort((u_hi, u_lo), num_keys=2)
            overflow = c["overflow"] | ~(
                (u_hi[C] == jnp.uint32(_SENT)) & (u_lo[C] == jnp.uint32(_SENT))
            )
            v_hi, v_lo = u_hi[:C], u_lo[:C]

            # Sort#4: compact the new states' candidate positions into
            # the next frontier (new rows first, in candidate order).
            nf_pos = jnp.where(is_new, m_pos, jnp.uint32(_SENT))
            (nf_pos,) = lax.sort((nf_pos,), num_keys=1)
            nf_pos = nf_pos[:F]
            nf_valid = jnp.arange(F) < new_count
            f_overflow = c["f_overflow"] | (new_count > F)
            nf_row = jnp.where(nf_valid, nf_pos - 1, jnp.uint32(0))
            next_frontier = b_state[nf_row]
            next_ebits = jnp.where(nf_valid, b_ebits[nf_row], 0)

            # Parent-log append: contiguous block write at the running
            # offset (no scatter); rows past new_count are garbage that
            # the next wave's block overwrites.
            if track_paths:
                nc_lo = jnp.where(nf_valid, s_lo[nf_row], 0)
                nc_hi = jnp.where(nf_valid, s_hi[nf_row], 0)
                np_lo = jnp.where(nf_valid, b_par_lo[nf_row], 0)
                np_hi = jnp.where(nf_valid, b_par_hi[nf_row], 0)
                off = (c["pl_n"],)
                pl_child_lo = lax.dynamic_update_slice(
                    c["pl_child_lo"], nc_lo, off
                )
                pl_child_hi = lax.dynamic_update_slice(
                    c["pl_child_hi"], nc_hi, off
                )
                pl_par_lo = lax.dynamic_update_slice(
                    c["pl_par_lo"], np_lo, off
                )
                pl_par_hi = lax.dynamic_update_slice(
                    c["pl_par_hi"], np_hi, off
                )
                pl_n = c["pl_n"] + new_count.astype(jnp.uint32)
            else:
                pl_child_lo = c["pl_child_lo"]
                pl_child_hi = c["pl_child_hi"]
                pl_par_lo = c["pl_par_lo"]
                pl_par_hi = c["pl_par_hi"]
                pl_n = c["pl_n"]

            g = u64_add(
                U64(c["gen_lo"], c["gen_hi"]),
                U64(n_cand.astype(jnp.uint32), jnp.uint32(0)),
            )
            new = c["new"] + new_count.astype(jnp.uint32)
            all_disc = (
                jnp.all(disc_found) if n_props else jnp.bool_(False)
            )
            if target_states is None:
                target_hit = jnp.bool_(False)
            else:
                target_hit = new >= jnp.uint32(target_states)
            cont = (
                (new_count > 0)
                & ~all_disc
                & ~target_hit
                & ~overflow
                & ~f_overflow
                & ~c_overflow
            )
            return dict(
                v_lo=v_lo,
                v_hi=v_hi,
                pl_child_lo=pl_child_lo,
                pl_child_hi=pl_child_hi,
                pl_par_lo=pl_par_lo,
                pl_par_hi=pl_par_hi,
                pl_n=pl_n,
                frontier=next_frontier,
                fval=nf_valid & cont,
                ebits=next_ebits,
                depth=jnp.where(cont, c["depth"] + 1, c["depth"]),
                wchunk=c["wchunk"] + 1,
                waves=c["waves"] + 1,
                gen_lo=g.lo,
                gen_hi=g.hi,
                new=new,
                disc_found=disc_found,
                disc_lo=disc_lo,
                disc_hi=disc_hi,
                overflow=overflow,
                f_overflow=f_overflow,
                c_overflow=c_overflow,
                done=~cont,
            )

        def cond(c):
            return ~c["done"] & (c["wchunk"] < waves_per_sync)

        def chunk(carry):
            c = dict(carry, wchunk=jnp.int32(0))
            c = lax.while_loop(cond, body, c)
            scalars = jnp.stack(
                [
                    c["done"].astype(jnp.uint32),
                    c["overflow"].astype(jnp.uint32),
                    c["f_overflow"].astype(jnp.uint32),
                    c["depth"].astype(jnp.uint32),
                    c["waves"],
                    jnp.sum(c["fval"]).astype(jnp.uint32),
                    c["gen_lo"],
                    c["gen_hi"],
                    c["new"],
                    c["c_overflow"].astype(jnp.uint32),
                ]
            )
            stats = jnp.concatenate(
                [
                    scalars,
                    c["disc_found"].astype(jnp.uint32),
                    c["disc_lo"],
                    c["disc_hi"],
                ]
            )
            return c, stats

        return jax.jit(seed), jax.jit(chunk, donate_argnums=0)

    # -- reconstruction ----------------------------------------------------

    def _capture_final(self, carry) -> None:
        self._final_tables = (
            carry["pl_child_lo"],
            carry["pl_child_hi"],
            carry["pl_par_lo"],
            carry["pl_par_hi"],
            carry["pl_n"],
        )

    def _build_generated(self):
        """Materialize child→parent from the append-only device log
        (the lazy download; roots are simply absent from the log)."""
        if self.generated is None:
            c_lo, c_hi, p_lo, p_hi, pl_n = (
                np.asarray(a) for a in self._final_tables
            )
            n = int(pl_n)
            child = (
                c_hi[:n].astype(np.uint64) << np.uint64(32)
            ) | c_lo[:n].astype(np.uint64)
            parent = (
                p_hi[:n].astype(np.uint64) << np.uint64(32)
            ) | p_lo[:n].astype(np.uint64)
            self.generated = {
                int(c): (int(p) if p else None)
                for c, p in zip(child.tolist(), parent.tolist())
            }
        return self.generated
