"""The adaptive sort-merge wave engine: dedup without scatters, sized
to the running wave.

TPU microbenchmarks (v5e, round 2; re-runnable at current shapes via
``tools/profile_stages.py --micro``) show the hash
table engine's cost profile is inverted on TPU hardware: arbitrary-
index scatter/gather — the heart of GPU-style open-addressing
(ops/hashset.py) — runs ~10ns/row, and a 21-step binary search over a
sorted 2M-row array costs 2,085ms for 4M queries (sequential gathers),
while ``lax.sort`` moves 4M 3-lane rows in 6.5ms. XLA:TPU lowers
scatters to serialized updates; sorts are native and fast. So this
engine architects the wave around sorts, the classic vector-machine
model-checking layout:

* The visited set is a **sorted fingerprint array** (two uint32 limb
  lanes, all-ones sentinel padding), not a hash table — and since
  round 10 it is kept **incrementally sorted**: every wave merges its
  winners into the sorted prefix with a streaming linear merge
  (``ops/merge.py`` — a Pallas kernel on chip, a sort-free XLA
  fallback elsewhere), so no per-wave pass ever sorts O(C) rows.
* Per wave: vmap-expand the frontier → fingerprint candidates →
  compact the valid candidates (tiled top-B sorts) → ONE B-row
  candidate order sort + a streaming membership pass against the
  sorted visited prefix (visited wins ties; intra-wave duplicates
  resolve on the adjacent-equal check, first-of-buffer-order wins) →
  linear-merge the ≤F winner keys into the visited prefix → compact
  the new states into the next frontier.
* The parent forest is an **append-only device log** of
  (child, parent) fingerprint pairs written with
  ``dynamic_update_slice`` — contiguous writes, no scatter — drained
  lazily on the host only when a counterexample path is reconstructed.

**Adaptive wave sizing (round 3).** The round-2 engine compiled ONE
wave program at worst-case shapes, so every wave paid peak cost: the
2pc rm=8 profile showed a flat ~365ms/wave whether the wave produced
2 or 244,342 new states (the round-2 wave profile; per-wave walls now
come from ``--trace=deep`` + tools/latency_report.py), dominated by a
22M-row sort over the full F×K candidate tensor and a 4M-row payload
gather. This engine instead compiles a LADDER of wave-body variants
and dispatches per wave with ``lax.switch`` — still inside the
device-resident ``lax.while_loop``, so the host still syncs only once
per chunk:

* **frontier class** — the frontier is always a compacted prefix, so
  a wave with n live rows runs the smallest variant with F_c ≥ n:
  expansion, fingerprinting, and candidate compaction all scale with
  the running wave, not the worst one.
* **visited class** — the visited array is sorted with sentinel
  padding, so only the prefix holding the current unique count needs
  to participate in the merge; the merge stage is a nested switch
  over visited-prefix sizes.
* **tiling** — within a class, candidate compaction runs as NT
  per-tile top-B sorts (lax.sort is superlinear: 22M rows cost 109ms
  where 16×1.4M cost ~40ms).
* **full-flat mode** — when the class's F×K×W successor tensor fits
  the memory budget it is kept alive through the merge, and only the
  ≤F winning rows are gathered at the end of the wave (the round-2
  engine gathered all B candidate payloads every wave: ~10ns/row ≈
  40ms/wave at rm=8). Classes too big for the budget fall back to
  per-tile payload gathers.

Everything else — the device-resident multi-wave ``lax.while_loop``,
packed-stats chunk sync, properties/EventuallyBits/discovery logic —
is shared with :mod:`stateright_tpu.checkers.tpu`.

**Transposed resident layout + collapsed ladder carries (round 9,
PERF.md §layout).** Resident state lives COLUMN-major:

* the frontier is ``uint32[W, F]`` (minor dim = rows, so the
  T(8,128) tile tax on every elementwise/fold pass — and on every
  carry copy the class-ladder switches materialize — vanishes; the
  fingerprint fold measured 1.65x col-major on chip),
* the visited keys are one SoA block ``vkeys: uint32[2, C_pad]``
  (lane 0 = lo limb, lane 1 = hi limb), rows ``[0, new)`` a dense
  SORTED prefix of real keys (round 10),
* the parent log is ``plog: uint32[4, L]`` — parent limbs in lanes
  0-1, child limbs in lanes 2-3. (Round 9 derived the children from
  ``vkeys`` at drain — the visited append then WAS the insertion
  order; the round-10 sorted merge re-orders the visited rows every
  wave, so the log carries the child keys again. The two extra lanes
  exist only when ``track_paths`` allocates the log at all; the
  headline perf lanes run paths-off with ``L = 0``.)

**Incrementally sorted visited + streaming merge (round 10, PERF.md
§merge-kernel).** Rounds 5-9 kept the visited array append-only and
unsorted, paying a from-scratch ``(V_v + B)``-row stable 3-lane
``lax.sort`` every wave to dedup — the irreducible b·V floor the
wave-wall work kept exposing. The sorted invariant replaces that
rebuild with B-scale sorts plus two O(V + B) streaming passes:

* ONE ``B_eff``-row stable sort orders the wave's candidates (the
  only place candidate keys are sorted; the tiled compaction's
  per-tile sorts remain, but nothing re-sorts visited rows),
* membership rides a streaming pass over the sorted visited prefix
  (``ops.merge.member_sorted`` — Pallas linear merge on chip, 2-limb
  binary search on the XLA fallback), and intra-wave duplicates
  resolve on the adjacent-equal check of the sorted candidates
  (stable sort ⇒ lowest buffer position wins, matching the old
  stable-concat winner exactly),
* the visited append is a linear merge of the ≤F sorted winner keys
  into the prefix (``ops.merge.merge_sorted``), written back as one
  class-local ``dynamic_update_slice`` block under the v-class
  switch.

The ``merge_impl`` knob (None = auto: Pallas on TPU, XLA fallback on
CPU/old JAX; ``"pallas_interpret"`` runs the same kernel under the
Pallas interpreter so tier-1 pins it on CPU) selects the
implementation; it is cache-keyed and recorded in lane configs and
bench provenance.

Boundary transposes happen only at host upload/download and at the
table-gather seams where row-major genuinely wins (PERF.md §gathers:
payload gathers measured equal either way, so gather staging keeps
``[N, W]`` rows; the per-wave ``frontier_t.T`` feeding the pair-step
row gathers is the one sanctioned seam copy).

The (f, v) class ladder no longer copies full carry tuples between
branches: the v-class switch runs the B-scale membership pass (its
only branch output is a ``bool[B_eff]`` mask), ONE fetch-class switch
per wave updates frontier/ebits/plog with class-local
``dynamic_update_slice`` blocks, a second v-class switch merges the
winner keys into ``vkeys`` (its only branch output is the updated
``vkeys`` buffer), and the next carry is assembled outside any
switch. The ``carry-copy-bytes`` lint rule (GATED,
analysis/tables.py budgets) pins the collapse.
"""

from __future__ import annotations

import numpy as np

from ..encoding import (
    SparseEncodedModel,
    has_trivial_boundary,
    pair_step_seam,
    within_boundary_cols,
)
from ..model import Expectation
from ..ops.bitmask import mask_words
from ..ops.fingerprint import fingerprint_u32v, fingerprint_u32v_t
from ..ops.merge import (
    compact_winners,
    member_sorted,
    merge_sorted,
    resolve_impl,
)
from ..ops.u64 import U64, u64_add
from .tpu import (
    TpuBfsChecker,
    _monitor_snapshot,
    discovery_update,
    expand_frontier,
    frontier_props_t,
)

_SENT = 0xFFFFFFFF

#: compiled TIERED chunk programs (stateright_tpu/tier.py), keyed by
#: the untiered program identity + the tier marker — a separate cache
#: from tpu._CHUNK_CACHE so tiered builds never touch the untiered
#: entries (or their _build_info/_carry_pspecs riders).
_TIER_CACHE: dict = {}


def payload_width(W: int, track_paths: bool) -> int:
    """Lanes of the packed candidate payload (see payload_pack)."""
    return W + 3 + (2 if track_paths else 0)


def payload_pack(jnp, state, key_lo, key_hi, ebits, par_lo=None,
                 par_hi=None):
    """THE single-chip packed-payload lane layout:
    ``[state 0:W | key_lo W | key_hi W+1 | ebits W+2 | par_lo W+3 |
    par_hi W+4]`` — every SINGLE-CHIP pack site and fetch unpack goes
    through this pair so those call sites can't drift (round-5 review
    finding). The sharded engine's routed destination tiles use a
    DIFFERENT lane order and their own named helper
    (parallel/engine_sortmerge.py ``dest_tile_pack``) — the two
    layouts never meet: this one is unpacked by :func:`payload_unpack`
    at the merge fetch, that one by the post-shuffle merge."""
    parts = [state, key_lo[:, None], key_hi[:, None], ebits[:, None]]
    if par_lo is not None:
        parts += [par_lo[:, None], par_hi[:, None]]
    return jnp.concatenate(parts, axis=1)


def payload_unpack(p, W: int, track_paths: bool):
    """Inverse of payload_pack, in the merge-fetch return order:
    ``(state, par_lo, par_hi, ebits, key_lo, key_hi)``."""
    return (
        p[:, :W],
        p[:, W + 3] if track_paths else None,
        p[:, W + 4] if track_paths else None,
        p[:, W + 2],
        p[:, W],
        p[:, W + 1],
    )


def _ladder(lo: int, hi: int, step: int) -> list[int]:
    """Geometric size ladder [min(lo,hi), ..., hi] with ratio `step`."""
    vals = []
    v = min(lo, hi)
    while v < hi:
        vals.append(v)
        v *= step
    vals.append(hi)
    return vals


def _divisor_at_least(n: int, want: int) -> int:
    """Smallest divisor of n that is ≥ want (≤ n)."""
    d = max(min(want, n), 1)
    while n % d:
        d += 1
    return d


def frontier_enabled_bits(enc, frontier_t, fval_f, expand, *,
                          mask_budget_cells, n_rows=None, pv=None,
                          ample_words=None):
    """The enabled-bitmap pass of :func:`sparse_pair_candidates` —
    per-row packed ``uint32[F_f, L]`` words plus per-row enabled
    counts over the transposed ``[W, F]`` block, tiled through a
    ``fori_loop`` when ``F_f * K`` exceeds the mask-cell budget (so
    the dense ``[F, K]`` bool never materializes at large F).

    ONE home shared with tools/profile_stages.py's mask stage, the
    same way ``encoding.pair_step_seam`` is the one home of the pair
    gather seam: a mask-path change that lands here is the pipeline
    the profiler times, by construction — no hand-synced mirror to
    drift. ``pv`` marks loop-carry seeds shard-varying under
    ``shard_map`` (identity otherwise).

    ``ample_words`` (off by default) is a host-constant packed
    ``uint32[L]`` ample-set mask (ops/canonical.py companion: the
    partial-order-reduction filter the encoding precomputes,
    ``ample_mask_host``): the filter is ONE word-AND folded into this
    pass — slots outside the ample set never reach the peel, the
    compaction, or the candidate counts. The encoding owns the
    soundness argument for its mask; engines only apply it."""
    import jax.numpy as jnp
    from jax import lax

    from ..encoding import enabled_bits_cols, enabled_mask_cols
    from ..ops.bitmask import mask_to_words, popcount_words

    if pv is None:
        pv = lambda x: x  # noqa: E731 — identity outside shard_map
    W = frontier_t.shape[0]
    F_f = int(n_rows) if n_rows is not None else frontier_t.shape[1]
    K = enc.max_actions
    L = mask_words(K)
    bits_fn = getattr(enc, "enabled_bits_vec", None)
    aw = (None if ample_words is None
          else jnp.asarray(np.asarray(ample_words, np.uint32)))

    def mask_bits(tf_t, tfv):
        if bits_fn is not None:
            tb = enabled_bits_cols(enc, tf_t)
            tb = jnp.where(expand, tb, jnp.uint32(0))
            tb = jnp.where(tfv[:, None], tb, jnp.uint32(0))
            if aw is not None:
                tb = tb & aw[None, :]
            return tb, popcount_words(jnp, tb)
        m = enabled_mask_cols(enc, tf_t)
        m = m & tfv[:, None] & expand
        w = mask_to_words(jnp, m)
        if aw is not None:
            # counts must see the filtered bitmap too
            w = w & aw[None, :]
            return w, popcount_words(jnp, w)
        tc = jnp.sum(m, axis=1, dtype=jnp.uint32)
        return w, tc

    if F_f * K > mask_budget_cells:
        NTm = _divisor_at_least(F_f, -(-F_f * K // mask_budget_cells))
        Tm = F_f // NTm

        def mtile(ti, acc):
            bits_a, cnt_a = acc
            off = ti * Tm
            tf_t = lax.dynamic_slice(frontier_t, (0, off), (W, Tm))
            tfv = lax.dynamic_slice(fval_f, (off,), (Tm,))
            tb, tc = mask_bits(tf_t, tfv)
            bits_a = lax.dynamic_update_slice(bits_a, tb, (off, 0))
            cnt_a = lax.dynamic_update_slice(cnt_a, tc, (off,))
            return bits_a, cnt_a

        return lax.fori_loop(
            0,
            NTm,
            mtile,
            (
                pv(jnp.zeros((F_f, L), jnp.uint32)),
                pv(jnp.zeros(F_f, jnp.uint32)),
            ),
        )
    # untiled: the class view fuses into the elementwise mask pass
    # (no loop operand, so no materialized copy)
    return mask_bits(frontier_t[:, :F_f], fval_f)


def sparse_pair_candidates(enc, frontier_t, fval_f, expand, *, EV, B_p,
                           NT, T, mask_budget_cells, Ba,
                           axis_name=None, n_rows=None,
                           ample_words=None):
    """The sparse-dispatch pair pipeline, shared by the single-chip and
    sharded sort-merge engines (PERF.md §sparse): per-slot enabled
    mask → per-row bitmaps (tiled so the [F, K] bool mask never
    materializes at large F) → lowest-set-bit peel into ≤EV slots per
    row → tiled 1-lane packed-append compaction into a [Ba] buffer of
    pair indices.

    ``frontier_t`` is the TRANSPOSED resident block ``uint32[W, F_f]``
    (PERF.md §layout): the enabled predicate batches over axis 1
    (``enabled_bits_cols`` — per-state lane reads become contiguous
    row slices of the [W, N] block) and everything downstream of the
    bitmap is row-count-indexed exactly as before.

    ``n_rows`` lets an engine pass its FULL resident ``[W, F]``
    buffer with the class width F_f given explicitly: a column-prefix
    slice of the transposed layout is STRIDED, and a strided slice
    that becomes a ``fori_loop`` operand (the tiled mask loop below)
    forces XLA to materialize a per-wave copy of the whole class
    prefix — the full carry buffer aliases for free. Tile slices are
    taken from the full buffer at class-bounded offsets; only the
    untiled elementwise mask pass (which fuses) sees a sliced view.

    Encodings that build the packed words directly
    (``enabled_bits_vec`` — the compiled actor codegen) skip the dense
    ``bool[K]`` mask entirely: the engine consumes ``uint32[L]`` rows
    and counts by popcount, so no [tile, K] bool tensor exists even
    per tile (PERF.md §ordered: the compiled mask tax).

    Returns ``(pidx[Ba], live[Ba], pslot[Ba], cnt[F_f], n_pairs,
    pair_ovf, tile_max)`` — ``pair_ovf`` is True when a row enabled
    more than EV slots or the wave enabled more than B_p pairs.

    Codegen contract (pinned by ``pytest -m lint`` /
    tools/lint_kernels.py for every registered encoding, in BOTH
    invocation styles — this direct call and the sharded engine's
    ``axis_name="shard"`` call under ``shard_map``): no dense
    ``[F, K]`` bool anywhere, no gather anywhere; the bitmap
    predicate, peel, and packed-append compaction are elementwise +
    sort only (stateright_tpu/analysis/).
    """
    import jax.numpy as jnp
    from jax import lax

    F_f = int(n_rows) if n_rows is not None else frontier_t.shape[1]
    K = enc.max_actions
    L = mask_words(K)
    NPg = F_f * EV
    compaction = NPg > B_p

    def pv(x):
        """Inside shard_map, fori_loop carries seeded from constants
        are 'unvarying' while the body outputs vary per shard — mark
        the seeds as shard-varying to keep carry types equal. (Older
        jax has no pvary and no unvarying carry typing: identity.)"""
        if axis_name is None or not hasattr(lax, "pvary"):
            return x
        return lax.pvary(x, axis_name)

    bits, cnt = frontier_enabled_bits(
        enc, frontier_t, fval_f, expand,
        mask_budget_cells=mask_budget_cells, n_rows=n_rows, pv=pv,
        ample_words=ample_words,
    )
    n_pairs = jnp.sum(cnt, dtype=jnp.uint32)
    pair_ovf = jnp.any(cnt > jnp.uint32(EV)) | (
        n_pairs > jnp.uint32(B_p)
    )

    # Peel the lowest set bit per row, EV times — pure elementwise
    # [F, L] passes plus a min-reduce along L (argmax/take_along_axis
    # formulations lower to slow gathers on TPU: measured
    # ~6ms/iteration vs <0.5ms for this form at F=2^18, L=9).
    lane_base = (
        jnp.arange(L, dtype=jnp.uint32) * jnp.uint32(32)
    )[None, :]
    lanes = bits
    slot_cols, val_cols = [], []
    for _ in range(EV):
        low = lanes & (jnp.uint32(0) - lanes)
        pos = lax.population_count(low - jnp.uint32(1))
        cand = jnp.where(
            lanes != 0, lane_base + pos, jnp.uint32(_SENT)
        )
        slot = jnp.min(cand, axis=1)
        any_ = slot != jnp.uint32(_SENT)
        slot_cols.append(jnp.where(any_, slot, jnp.uint32(0)))
        val_cols.append(any_)
        lanes = jnp.where(
            cand == slot[:, None],
            lanes & (lanes - jnp.uint32(1)),
            lanes,
        )
    slots_flat = jnp.stack(slot_cols, axis=1).reshape(NPg)
    valid_g = jnp.stack(val_cols, axis=1)

    pair_idx = (
        jnp.arange(F_f, dtype=jnp.uint32)[:, None] * jnp.uint32(EV)
        + jnp.arange(EV, dtype=jnp.uint32)[None, :]
    )
    keys = jnp.where(valid_g, pair_idx, jnp.uint32(_SENT)).reshape(NPg)

    if compaction:
        # Tiled packed-append compaction (the sparse analog of the
        # dense tiled key compaction; sort is superlinear so NT small
        # sorts beat one big one). The slot rides the sort as a VALUE
        # lane so no post-compaction ``slots_flat[pidx]`` gather is
        # needed (PERF.md §gathers: one Ba-row gather ≈ a whole extra
        # sort).
        def tile_body(ti, acc):
            pk, ps, app_off, tmax = acc
            off = ti * (T * EV)
            tk = lax.dynamic_slice(keys, (off,), (T * EV,))
            ts = lax.dynamic_slice(slots_flat, (off,), (T * EV,))
            tc = jnp.sum(tk != jnp.uint32(_SENT), dtype=jnp.uint32)
            tmax = jnp.maximum(tmax, tc)
            sk, ss = lax.sort((tk, ts), num_keys=1)
            pk = lax.dynamic_update_slice(pk, sk, (app_off,))
            ps = lax.dynamic_update_slice(ps, ss, (app_off,))
            return pk, ps, app_off + tc, tmax

        pk, psl, _, tile_max = lax.fori_loop(
            0,
            NT,
            tile_body,
            (
                pv(jnp.full(Ba, _SENT, jnp.uint32)),
                pv(jnp.zeros(Ba, jnp.uint32)),
                pv(jnp.uint32(0)),
                pv(jnp.uint32(0)),
            ),
        )
    else:
        pk = keys
        psl = slots_flat
        tile_max = n_pairs

    live = pk != jnp.uint32(_SENT)
    pidx = jnp.where(live, pk, jnp.uint32(0))
    pslot = jnp.where(live, psl, jnp.uint32(0))
    return pidx, live, pslot, cnt, n_pairs, pair_ovf, tile_max


class SortMergeTpuBfsChecker(TpuBfsChecker):
    """``CheckerBuilder.spawn_tpu_sortmerge()``.

    ``capacity`` is the visited-array length — unlike the hash table
    there is no load-factor pressure: it can sit at exactly the
    expected unique-state count (overflow is detected, not silent).

    ``cand_capacity`` is the per-wave candidate budget for the LARGEST
    frontier class; smaller classes use min(F_c*K, cand_capacity).
    Overflow is never silent: the full-flat path checks the whole-wave
    candidate count against the budget (packed tile append has no
    per-tile budget); the per-tile-payload fallback checks per tile.

    ``tiles`` forces at least that many expansion tiles on the largest
    frontier class (smaller classes tile automatically so no single
    compaction sort exceeds ``tile_rows`` rows).

    ``f_min``/``v_min``/``ladder_step`` shape the adaptive ladders; a
    small model (F ≤ f_min, capacity ≤ v_min) degenerates to a single
    fixed-shape wave program, which is also the fallback the test
    suite exercises at toy scale.
    """

    #: Device symmetry capability (checkers/common.symmetry_refusal):
    #: the sort-merge engine canonicalizes candidate blocks before the
    #: fingerprint fold (ops/canonical.py) when the encoding declares
    #: a DeviceRewriteSpec — the base TpuBfsChecker refuses instead.
    _supports_device_symmetry = True
    _engine_name = "spawn_tpu_sortmerge"

    def __init__(
        self,
        builder,
        tiles: int = 1,
        tile_rows: int = 1 << 21,
        f_min: int = 1 << 15,
        v_min: int = 1 << 19,
        ladder_step: int = 2,
        # Round 10 re-derivation: the v-ladder now prices LINEARLY —
        # the streaming passes cost a·V_v + b·B, so a class step of s
        # wastes at most (s-1)x of the V-term on the worst wave
        # (round 6's superlinear-sort argument priced the same waste
        # at (s-1)·log extra compare passes). At step 2 the bound is
        # 2x on a term that is now ~5x cheaper per row (PERF.md
        # §merge-kernel CPU A/B); step 4 would re-expose up to 4x of
        # it for ~half the compiled merge variants — the compile time
        # the persistent XLA cache already absorbs. 2 stays optimal.
        v_ladder_step: int = 2,
        flat_budget_bytes: int = 1 << 30,
        sparse: bool | None = None,
        pair_width: int | None = None,
        mask_budget_cells: int = 1 << 23,
        merge_impl: str | None = None,
        tier_hot_rows=None,
        tier_budget_bytes: int | None = None,
        tier_max_runs: int = 8,
        ample_set: bool = False,
        **kwargs,
    ):
        #: ``cand_capacity="auto"`` (VERDICT r4 item 7): size the
        #: candidate budget (and, in sparse mode, pair_width) from
        #: MEASURED wave peaks instead of a hand-tuned table. The
        #: first run starts from a persisted budget (or a growth
        #: heuristic), and a loud overflow triggers an automatic
        #: resize-and-rerun from the exact observed peak — the same
        #: metric a human re-tuner would read — then persists it for
        #: later processes (~/.cache/stateright_tpu_budgets.json).
        self.auto_budget = kwargs.get("cand_capacity") == "auto"
        if self.auto_budget:
            kwargs["cand_capacity"] = None
        super().__init__(builder, **kwargs)
        self.tiles = tiles
        self.tile_rows = tile_rows
        self.f_min = f_min
        self.v_min = v_min
        self.ladder_step = ladder_step
        self.v_ladder_step = v_ladder_step
        self.flat_budget_bytes = flat_budget_bytes
        #: sparse action dispatch (None = auto: on iff the encoding
        #: implements SparseEncodedModel). pair_width bounds the
        #: enabled slots extracted per frontier row per wave (overflow
        #: detected, never silent); None defers to the encoding's
        #: ``pair_width_hint`` and finally to max_actions, which can
        #: never overflow per-row.
        self.sparse = sparse
        self.pair_width = pair_width
        self.mask_budget_cells = mask_budget_cells
        #: visited-dedup implementation (ops/merge.py): None = auto
        #: (Pallas kernel on TPU, sort-free XLA fallback on CPU/old
        #: JAX); "pallas_interpret" runs the kernel under the Pallas
        #: interpreter — the tier-1 CPU gate for the kernel itself.
        self.merge_impl = resolve_impl(merge_impl)
        #: Tiered visited set (stateright_tpu/tier.py, ROADMAP 1b):
        #: None = off (the all-resident engine, byte-identical to
        #: round 15); an int = the hot-tier ladder ceiling in visited
        #: rows (tests force it tiny to spill repeatedly); "auto" =
        #: decided by the memplan capacity projection against
        #: ``tier_budget_bytes`` (memplan.decide_hot_rows — the
        #: projection is exactly the split signal). Host-side only:
        #: the untiered chunk programs compile byte-identically; the
        #: tiered program is a second, separately-keyed program built
        #: lazily at the first spill.
        self.tier_hot_rows = tier_hot_rows
        self.tier_budget_bytes = tier_budget_bytes
        self.tier_max_runs = tier_max_runs
        #: Partial-order-reduction ample-set filter (off by default):
        #: AND the encoding's host-precomputed ample_mask_host() words
        #: into the packed enabled bitmap, dropping redundant
        #: interleavings before pair compaction. Sparse path only —
        #: the mask is a bitmap-domain object. The ENCODING owns the
        #: soundness argument for its mask (see
        #: two_phase_commit_tpu.ample_mask_host); the engine only
        #: validates shape and applies the AND.
        self.ample_set = bool(ample_set)
        #: tiered-mode frontier-headroom pre-check policy
        #: (memplan.tier_frontier_headroom, checked BEFORE device
        #: work): "warn" — surface the PR 12 known bound up front
        #: (default; the old behavior surfaced it only as a mid-run
        #: f_overflow message), "bump" — raise frontier_capacity to
        #: the provable bound before programs build, "refuse" —
        #: raise instead of risking a mid-run overflow.
        self.tier_headroom_policy = "warn"
        self._tier_headroom_checked = False
        #: the live ColdStore while a tiered run is in flight, and
        #: the resume-staged tier state (checkpoint.resume_from)
        self._tier_state = None
        self._tier_resume_state = None
        self._tier_hot_ceiling = None
        self._tier_spill_wall = 0.0
        self._tier_plog_rows = None
        if tiles > 1 and self.frontier_capacity % tiles:
            raise ValueError(
                f"frontier_capacity {self.frontier_capacity} not divisible "
                f"by tiles {tiles}"
            )
        if self.auto_budget:
            saved = self._load_budget()
            if saved is not None:
                self.cand_capacity = saved["cand_capacity"]
                # A persisted pair_width only fills the default: an
                # EXPLICIT constructor pair_width wins over the store
                # (cand_capacity="auto" silently widening a passed
                # pair_width was ADVICE r5).
                if (self._use_sparse() and saved.get("pair_width")
                        and self.pair_width is None):
                    self.pair_width = saved["pair_width"]
            else:
                # Growth heuristic: a wave rarely multiplies the
                # frontier by more than a few; overflow (loud) corrects
                # upward from the measured peak.
                F = self.frontier_capacity
                K = self.encoded.max_actions
                self.cand_capacity = min(
                    4 * F, F * (self._pair_width()
                                if self._use_sparse() else K)
                )

    @property
    def codegen_opt(self):
        """Codegen-optimizer summary of the encoding this engine is
        executing (actor/compile.py ``CompiledActorEncoding.codegen_opt``
        — fused switch / elided gathers / table widths), or ``None``
        for hand encodings and ``optimize=False`` compiles. One seam
        for bench detail + provenance on both sort-merge engines (the
        sharded engine inherits it)."""
        return getattr(self.encoded, "codegen_opt", None)

    # -- auto budget (VERDICT r4 item 7) -----------------------------------

    def _budget_store(self):
        import os

        return os.path.expanduser(
            "~/.cache/stateright_tpu_budgets.json"
        )

    def _budget_key(self) -> str:
        enc = self.encoded
        key_fn = getattr(enc, "cache_key", None)
        ident = repr(key_fn()) if key_fn is not None else ""
        return (
            f"{type(enc).__name__}/{ident}/W{enc.width}/"
            f"K{enc.max_actions}/F{self.frontier_capacity}/"
            f"C{self.capacity}"
        )

    def _load_budget(self):
        import json
        import os

        path = self._budget_store()
        if not os.path.exists(path):
            return None
        try:
            with open(path) as fh:
                data = json.load(fh)
        except ValueError as exc:
            # Torn-write hardening: a truncated/corrupt store (a crash
            # mid-write from a pre-atomic version, disk-level
            # truncation) must not raise at engine START — fall back
            # to the growth heuristic with one line saying why (the
            # next clean run's _save_budget rewrites the store
            # atomically). Parse-guard IS the checksum here: the
            # store is JSON, and torn JSON does not parse.
            import warnings

            warnings.warn(
                f"auto-budget store {path} is corrupt ({exc}); "
                "falling back to default budgets (the store rewrites "
                "on the next clean run)",
                RuntimeWarning,
                stacklevel=3,
            )
            return None
        except OSError:
            return None
        try:
            return data.get(self._budget_key())
        except AttributeError:
            return None

    def _save_budget(self) -> None:
        import json
        import os

        path = self._budget_store()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # Concurrent checkers (pytest workers, multi-model drivers)
        # write different keys into one store: an unlocked
        # read-modify-write dropped the loser's entry (ADVICE r5).
        # Serialize the whole cycle on a lock file so the re-read
        # immediately before the atomic replace sees every earlier
        # writer's keys.
        with open(path + ".lock", "w") as lock_fh:
            try:
                import fcntl

                fcntl.flock(lock_fh, fcntl.LOCK_EX)
            except (ImportError, OSError):
                # Non-POSIX, or a filesystem without flock support
                # (NFS/overlay): fall back to the unlocked-but-atomic
                # replace rather than failing a finished check run.
                pass
            data = {}
            try:
                with open(path) as fh:
                    data = json.load(fh)
            except (OSError, ValueError):
                pass
            data[self._budget_key()] = {
                "cand_capacity": self._shrunk_cand_capacity(),
                "pair_width": (
                    self._pair_width() if self._use_sparse() else None
                ),
                "observed_peak": self.metrics.get("max_wave_candidates"),
            }
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(data, fh, indent=1, sort_keys=True)
            os.replace(tmp, path)

    #: shrink target: persisted budget heads toward observed_peak *
    #: this margin on clean runs; shrink fires only past 2x headroom
    #: so a near-peak budget isn't thrashed by wave-to-wave noise.
    _SHRINK_MARGIN = 1.25

    def _shrunk_cand_capacity(self):
        """The cand_capacity to PERSIST (VERDICT/ROADMAP carried item):
        the budget store only ever grew, so a lane whose growth
        heuristic overshot kept its headroom forever — paxos-4
        converged at 2,097,152 against an observed peak of 660,492,
        3.2x slack that silently pushed the padded-residency gate into
        CHUNKED memory-lean mode and paid recompute fetch every wave.
        On a CLEAN run (no overflow retry this process — a just-grown
        budget is geometric, not measured, and must survive to the
        next run) with more than 2x headroom over the measured peak,
        persist ``observed_peak * margin`` instead; the running
        checker keeps its budget (programs are compiled), the next
        process picks up the shrunk one. Emits ``auto_budget_shrink``
        so TRACE artifacts show the resize."""
        cap = self.cand_capacity
        peak = self.metrics.get("max_wave_candidates")
        if (
            not cap
            or not peak
            or getattr(self, "_budget_grew", False)
        ):
            return cap
        want = max(int(peak * self._SHRINK_MARGIN), 1024)
        if cap <= 2 * want:
            return cap
        from .. import telemetry

        telemetry.emit(
            "auto_budget_shrink", kind="cand_capacity", old=cap,
            new=want, observed_peak=int(peak),
        )
        return want

    def _run(self, reporter=None) -> None:
        if not self.auto_budget:
            return super()._run(reporter)
        self._budget_grew = False
        last_exc = None
        for _attempt in range(6):
            if last_exc is not None:
                # Reset at the TOP of the retry so a final failed
                # attempt keeps its recorded discoveries (the
                # discoveries-survive-overflow contract in tpu.py).
                self._reset_for_retry()
            try:
                super()._run(reporter)
                self._save_budget()
                return
            except RuntimeError as exc:
                msg = str(exc)
                if ("pair-buffer overflow" not in msg
                        and "candidate-buffer overflow" not in msg):
                    raise
                last_exc = exc
                rowen = self.metrics.get("max_row_enabled", 0)
                if (self._use_sparse()
                        and rowen > self._pair_width()):
                    # The mask counts are exact even on the overflow
                    # run, so one resize suffices for pair_width —
                    # and a pure pair_width overflow must NOT also
                    # inflate (and persist) the candidate budget.
                    self._note_budget_growth(
                        "pair_width", self._pair_width(), int(rowen),
                        _attempt,
                    )
                    self.pair_width = int(rowen)
                    continue
                # The observed peak only covers waves BEFORE the
                # overflow, so grow geometrically past it — the
                # converged budget still ends within ~4x of the true
                # peak and one clean re-run records the exact value.
                peak = self.metrics.get("max_wave_candidates", 0)
                grown = max(
                    int(peak * 1.15) + 1024,
                    4 * (self.cand_capacity or 1),
                )
                self._note_budget_growth(
                    "cand_capacity", self.cand_capacity, grown,
                    _attempt,
                )
                self.cand_capacity = grown
        raise RuntimeError(
            "auto budget did not converge in 6 attempts; last overflow: "
            f"{last_exc}"
        ) from last_exc

    def _note_budget_growth(self, kind: str, old, new,
                            attempt: int) -> None:
        """The geometric capacity ladder used to retry SILENTLY: a
        run that overflowed and re-ran 3x read as 'slow', not
        'mis-budgeted'. Every resize now lands as a one-line warning
        naming the old/new capacity plus a telemetry event (when a
        tracer is active) so the retry shows up in TRACE artifacts."""
        import warnings

        from .. import telemetry

        # An overflow-grown budget is a geometric guess, not a
        # measurement: the clean-run shrink must not fire on it
        # (_shrunk_cand_capacity).
        self._budget_grew = True

        warnings.warn(
            f"auto-budget: {kind} {old} -> {new} after a buffer "
            f"overflow (retry {attempt + 1}); the resized wave "
            "programs recompile at the new shapes",
            RuntimeWarning,
            stacklevel=3,
        )
        telemetry.emit(
            "auto_budget_retry", kind=kind, old=old, new=new,
            attempt=attempt + 1,
        )

    def _reset_for_retry(self) -> None:
        """Discard one failed attempt's partial results so the resized
        re-run starts clean (programs rebuild at the new shapes).
        The memory ledger re-derives with them — a resized budget is
        a different class ladder."""
        self._programs = None
        self.memory_plan = None
        self._discovered_fps.clear()
        self._discoveries.clear()
        self._total_states = 0
        self._unique_states = 0
        self._max_depth = 0
        self.metrics = {}
        self.generated = None
        # a resized re-run re-explores (and re-spills) from scratch
        self._tier_state = None
        self._tier_plog_rows = None
        self._tier_mem = None

    def _checkpoint_family(self) -> str:
        # Both sort-merge engines carry the same sorted-prefix visited
        # structure, so their snapshots interconvert under the
        # (owner, fp) re-route (checkpoint.reshard_sortmerge).
        return "sortmerge"

    def _degrade_memory_lean(self) -> bool:
        """Supervisor OOM hook (checkpoint.supervised_run): quarter
        the flat budget so the padded-residency gates flip the big
        classes into CHUNKED memory-lean mode on the next attempt
        (the successor tensor is never materialized; winners
        recompute at fetch). Programs rebuild — flat_budget_bytes is
        cache-keyed, so the degraded shapes are a new entry."""
        new_budget = max(self.flat_budget_bytes // 4, 1 << 22)
        if new_budget >= self.flat_budget_bytes:
            return False
        import warnings

        from .. import telemetry

        warnings.warn(
            f"repeated OOM under supervision: flat_budget_bytes "
            f"{self.flat_budget_bytes} -> {new_budget} (CHUNKED "
            "memory-lean classes engage where the gate trips; "
            "programs recompile)",
            RuntimeWarning,
            stacklevel=3,
        )
        telemetry.emit(
            "oom_degrade", engine=type(self).__name__,
            flat_budget_bytes_old=int(self.flat_budget_bytes),
            flat_budget_bytes=int(new_budget),
        )
        self.flat_budget_bytes = new_budget
        self._programs = None
        self.memory_plan = None
        return True

    # -- tiered visited set (stateright_tpu/tier.py, ROADMAP 1b) -----------

    def _overflow_message(self, s):
        msg = super()._overflow_message(s)
        if (msg is not None and bool(s[1])
                and self.tier_hot_rows is not None):
            # the takeover only runs at chunk syncs: one UNTIERED
            # transition chunk can carry the resident count from the
            # ceiling past the capacity before the first spill fires
            msg += (
                "  (tiering is configured but the ceiling was "
                "crossed and overrun within one untiered chunk — "
                "lower waves_per_sync so a sync lands between the "
                "ceiling and the capacity, or lower tier_hot_rows)"
            )
        return msg

    def _pre_run_check(self) -> None:
        """The tiered frontier-headroom bound, pre-checked BEFORE any
        program build or device work (memplan.tier_frontier_headroom
        — the PR 12 known bound, which used to surface only as a
        mid-run f_overflow message): refuse, auto-bump the frontier
        to the provable bound, or warn up front, per
        ``tier_headroom_policy``."""
        if self.tier_hot_rows is None or self._tier_headroom_checked:
            return
        self._tier_headroom_checked = True
        from ..memplan import tier_frontier_headroom

        cand = self.cand_capacity
        if cand is None:
            # no compaction: the true static bound on a wave's
            # candidates (and therefore provisional winners) is F x K
            cand = self.frontier_capacity * self.encoded.max_actions
        chk = tier_frontier_headroom(
            self.capacity, self.frontier_capacity, cand
        )
        if chk["holds"] is not False:
            # True = provable; None = still-unresolved auto budget
            # (nothing provable or refutable before it lands)
            return
        policy = getattr(self, "tier_headroom_policy", "warn")
        if policy == "refuse":
            raise ValueError(
                "tiered frontier-headroom pre-check refused "
                "(tier_headroom_policy='refuse'): " + chk["message"]
            )
        import warnings

        if policy == "bump" and chk["required_frontier"]:
            old = self.frontier_capacity
            bumped = int(chk["required_frontier"])
            if self.tiles > 1 and bumped % self.tiles:
                bumped = (
                    (bumped + self.tiles - 1) // self.tiles
                ) * self.tiles
            self.frontier_capacity = bumped
            self._programs = None
            self.memory_plan = None
            warnings.warn(
                "tiered frontier-headroom pre-check: "
                f"frontier_capacity {old} -> {bumped} "
                "(tier_headroom_policy='bump' — provisional winners "
                "are bounded by cand_capacity="
                f"{self.cand_capacity}, so the bumped frontier makes "
                "the bound provable before device work)",
                RuntimeWarning,
                stacklevel=3,
            )
            return
        warnings.warn(chk["message"], RuntimeWarning, stacklevel=3)

    def _tier_ceiling(self):
        """The hot-tier ladder ceiling in visited rows (None = tier
        off). ``"auto"`` resolves through the memplan capacity
        projection's own pricing (memplan.decide_hot_rows) against
        ``tier_budget_bytes``."""
        if self.tier_hot_rows is None:
            return None
        if self._tier_hot_ceiling is None:
            if self.tier_hot_rows == "auto":
                from ..memplan import decide_hot_rows

                budget = self.tier_budget_bytes or (1 << 31)
                self._tier_hot_ceiling = decide_hot_rows(
                    self.capacity, self.v_min, self.v_ladder_step,
                    self.frontier_capacity, budget,
                )
            else:
                hr = int(self.tier_hot_rows)
                if hr < 1:
                    raise ValueError(
                        f"tier_hot_rows must be >= 1: {hr}"
                    )
                self._tier_hot_ceiling = min(hr, self.capacity)
        return self._tier_hot_ceiling

    def _tier_headroom(self):
        cold = self._tier_state
        if cold is None:
            return None
        out = cold.summary()
        out["hot_ceiling_rows"] = self._tier_hot_ceiling
        out["spill_wall_sec"] = round(self._tier_spill_wall, 6)
        return out

    def _reset_for_resume(self) -> None:
        super()._reset_for_resume()
        self._tier_state = None
        self._tier_plog_rows = None
        self._tier_mem = None

    def _tier_takeover(self, carry, n0, chunk_no, reporter):
        staged = self._tier_resume_state
        ceiling = self._tier_ceiling()
        if ceiling is None and staged is None:
            return None
        if staged is None:
            h_np = self._tier_resident_counts(carry)
            limit = min(
                ceiling, max(self.capacity - self.frontier_capacity, 1)
            )
            if int(h_np.max()) <= limit:
                return None
        return self._tier_run(carry, n0, chunk_no, reporter)

    def _lookup_tier_programs(self, n0: int):
        """Build-or-fetch the TIERED chunk program — a separate
        program (and cache slot) from the untiered pair, keyed by the
        same program identity plus the tier marker. Never touches the
        untiered cache entry, ``_wave_body``, or ``_build_info``."""
        key = self._program_cache_key(n0)
        if key is None:
            fn = self._build_programs(n0, tiered=True)
            return fn
        tkey = (key, "tiered")
        if tkey not in _TIER_CACHE:
            fn = self._build_programs(n0, tiered=True)
            _TIER_CACHE[tkey] = (
                fn, getattr(self, "_tier_pspecs", None)
            )
        fn, self._tier_pspecs = _TIER_CACHE[tkey]
        return fn

    # engine-shape hooks the shared loop uses (the sharded engine
    # overrides placement and the hot/pend lane layouts)

    def _tier_resident_counts(self, carry) -> np.ndarray:
        return np.array([int(np.asarray(carry["new"]))], np.int64)

    def _tier_hot_lane(self) -> str:
        return "n_hot"

    def _tier_zero_hot(self):
        return np.uint32(0)

    def _tier_hot_value(self, h_np):
        return np.uint32(int(h_np[0]))

    def _tier_zero_pl(self):
        return np.uint32(0)

    def _tier_place(self, name, arr):
        # jax-owned COPY: the tiered chunk donates its carry, and a
        # zero-copy upload aliasing numpy memory under donate_argnums
        # is the exact round-15 bug class (checkpoint.py)
        import jax.numpy as jnp

        return jnp.copy(jnp.asarray(arr))

    def _tier_mask_dev(self, mask_np: np.ndarray):
        import jax.numpy as jnp

        return jnp.asarray(np.ascontiguousarray(mask_np.reshape(-1)))

    def _tier_shard_rows(self, shard_log):
        return None

    def _tier_pend_zero(self):
        return np.uint32(0)

    def _tier_extend_carry(self, carry, h_np):
        """The handoff: untiered carry + the tiered staging lanes
        (empty pend, hot count, tier-shaped trace logs)."""
        S = getattr(self, "n_shards", 1)
        F = self.frontier_capacity
        ext = dict(carry)
        ext["pend_keys"] = self._tier_place(
            "pend_keys", np.full((2, S * F), _SENT, np.uint32)
        )
        if self.track_paths:
            ext["pend_par"] = self._tier_place(
                "pend_par", np.zeros((2, S * F), np.uint32)
            )
        ext["pend_n"] = self._tier_place(
            "pend_n", self._tier_pend_zero()
        )
        ext["pend_valid"] = self._tier_place(
            "pend_valid", np.bool_(False)
        )
        ext[self._tier_hot_lane()] = self._tier_place(
            self._tier_hot_lane(), self._tier_hot_value(h_np)
        )
        if self._wave_log_enabled():
            from ..telemetry import WAVE_LOG_LANES as WL

            ext["wlog"] = self._tier_place(
                "wlog", np.zeros((1, WL), np.uint32)
            )
            ext["pstash"] = self._tier_place(
                "pstash", np.zeros(8, np.uint32)
            )
            self._tier_extend_trace(ext)
        return ext

    def _tier_extend_trace(self, ext) -> None:
        """Hook: extra tier-shaped trace lanes (the sharded engine's
        per-shard mesh log)."""

    def _tier_pend_read(self, carry):
        S = getattr(self, "n_shards", 1)
        F = self.frontier_capacity
        pk = np.asarray(carry["pend_keys"]).reshape(2, S, F)
        pn = np.atleast_1d(
            np.asarray(carry["pend_n"])
        ).astype(np.int64).reshape(-1)
        return pn, pk[0], pk[1]

    def _tier_spill(self, carry, cold, h_np):
        """Spill the whole hot prefix to the cold store at the sync:
        the prefix download piggybacks the readback that just blocked
        (the checkpoint seam), ingest runs on the worker thread
        overlapped with the next dispatch, and the device hot tier
        resets to empty. Emits the schema-validated ``tier_spill``
        event."""
        import time as _time

        from .. import telemetry

        t0 = _time.monotonic()
        S = getattr(self, "n_shards", 1)
        C_pad = self.capacity + self.frontier_capacity
        vk = np.asarray(carry["vkeys"]).reshape(2, S, C_pad)
        per_shard = []
        for s_i in range(S):
            n = int(h_np[s_i])
            per_shard.append((
                vk[0, s_i, :n].copy(), vk[1, s_i, :n].copy()
            ))
        prev_rows = cold.rows()
        prev_runs = cold.run_count()
        cold.ingest(per_shard)
        carry = dict(carry)
        carry["vkeys"] = self._tier_place(
            "vkeys", np.full((2, S * C_pad), _SENT, np.uint32)
        )
        carry[self._tier_hot_lane()] = self._tier_place(
            self._tier_hot_lane(), self._tier_zero_hot()
        )
        wall = _time.monotonic() - t0
        self._tier_spill_wall += wall
        rows = int(h_np.sum())
        from ..tier import COLD_BYTES_PER_ROW

        telemetry.emit(
            "tier_spill",
            engine=type(self).__name__,
            rows=rows,
            bytes=rows * COLD_BYTES_PER_ROW,
            rows_per_shard=[int(x) for x in h_np],
            hot_rows_before=rows,
            hot_ceiling_rows=self._tier_hot_ceiling,
            spill_index=int(cold.spills),
            # pre-compaction run count (ingest is async; compaction
            # may fold runs before the next sync)
            runs=int(prev_runs + sum(1 for lo, _ in per_shard
                                     if lo.size)),
            cold_rows_total=int(prev_rows + rows),
            cold_bytes_total=int(
                (prev_rows + rows) * COLD_BYTES_PER_ROW
            ),
            wall_sec=round(wall, 6),
            ingest_sec=round(cold.ingest_sec, 6),
        )
        return carry

    def _tier_plog_reset(self, carry):
        """Take the device parent log's rows host-side and rewind the
        cursor — tiered runs outgrow the device log (it is sized for
        one capacity's worth of uniques, the cumulative count is
        unbounded), so the host accumulates the drained rows and
        ``_build_generated`` reads them instead."""
        S = getattr(self, "n_shards", 1)
        L = self.capacity + self.frontier_capacity
        pl = np.atleast_1d(
            np.asarray(carry["pl_n"])
        ).astype(np.int64).reshape(-1)
        plog = np.asarray(carry["plog"]).reshape(4, S, L)
        rows = []
        for s_i in range(S):
            n = int(pl[s_i])
            if n:
                rows.append(plog[:, s_i, :n].copy())
        carry = dict(carry)
        carry["pl_n"] = self._tier_place("pl_n", self._tier_zero_pl())
        return carry, rows

    def _tier_plog_drain(self, carry, pl_cursor, confs):
        """Per-dispatch drain of the rows the commit just appended
        (the host knows the count — it built the keep mask), with a
        cursor rewind when the device log nears its end. Slices
        DEVICE-side before materializing, so only the freshly
        appended ≤F rows transfer — not the whole [4, S*L] log
        (which would be a multi-MB D2H per wave on real HBM)."""
        S = getattr(self, "n_shards", 1)
        F = self.frontier_capacity
        L = self.capacity + F
        if int(confs.sum()):
            plog = carry["plog"]
            for s_i in range(S):
                cnf = int(confs[s_i])
                if cnf:
                    st = int(pl_cursor[s_i])
                    off = s_i * L + st
                    self._tier_plog_rows.append(
                        np.asarray(plog[:, off:off + cnf])
                    )
                    pl_cursor[s_i] = st + cnf
        if int(pl_cursor.max()) + F > L:
            carry = dict(carry)
            carry["pl_n"] = self._tier_place(
                "pl_n", self._tier_zero_pl()
            )
            pl_cursor[:] = 0
        return carry

    def _tier_generated_map(self):
        rows = getattr(self, "_tier_plog_rows", None)
        if rows is None:
            return None
        generated: dict = {}
        for blk in rows:
            child = (
                blk[3].astype(np.uint64) << np.uint64(32)
            ) | blk[2].astype(np.uint64)
            parent = (
                blk[1].astype(np.uint64) << np.uint64(32)
            ) | blk[0].astype(np.uint64)
            for ch, pa in zip(child.tolist(), parent.tolist()):
                generated[int(ch)] = int(pa) if pa else None
        return generated

    def _tier_run(self, carry, n0, chunk_no, reporter):
        """The tiered chunk loop (the host side of the deferred-commit
        protocol): per dispatch — commit the previous wave's survivors
        under the mask computed here, run one wave, read the new
        provisional winners at the one sync, run the batched
        sort-merge membership against the cold runs, spill the hot
        prefix when it crosses the ceiling. Returns the final
        ``(carry, stats)`` to the shared completion path in tpu.py."""
        import time as _time

        from .. import faultinject, telemetry
        from ..report import ReportData
        from ..telemetry import WAVE_LOG_LANES as WL
        from ..tier import ColdStore

        tracer = self._tracer
        S = getattr(self, "n_shards", 1)
        F = self.frontier_capacity
        C = self.capacity
        props = list(self.model.properties())
        n_props = len(props)
        trace_log = self._wave_log_enabled()
        ceiling = self._tier_ceiling()
        self._tier_hot_ceiling = ceiling
        limit = min(ceiling if ceiling else C, max(C - F, 1))

        staged = self._tier_resume_state
        self._tier_resume_state = None
        if staged is not None:
            cold = staged["cold"]
            cold.max_runs = self.tier_max_runs
            h_np = np.asarray(staged["hot"], np.int64).reshape(-1)
        else:
            cold = ColdStore(n_shards=S, max_runs=self.tier_max_runs)
            h_np = self._tier_resident_counts(carry)
        self._tier_state = cold

        tier_fn = self._lookup_tier_programs(n0)
        carry = self._tier_extend_carry(carry, h_np)
        if self.track_paths:
            carry, rows0 = self._tier_plog_reset(carry)
            # a resumed run's host-drained rows (snapshot tier_plog)
            # lead; then whatever the restored device log carried
            pre = (staged or {}).get("plog_rows") or []
            self._tier_plog_rows = list(pre) + rows0
        pl_cursor = np.zeros(S, np.int64)

        # first spill: activation means the ceiling is crossed (or
        # resumed cold runs exist with hot above it)
        if int(h_np.max()) > limit:
            carry = self._tier_spill(carry, cold, h_np)
            h_np = np.zeros(S, np.int64)

        verdicts_seen: set = set()
        d0 = np.asarray(carry["disc_found"])
        for i, prop in enumerate(props):
            if i < d0.size and d0[i]:
                verdicts_seen.add(prop.name)

        lat = self._lat
        mem_peak = None
        mem_src = None
        mem_polls = 0
        prev_waves = int(np.asarray(carry["waves"]))
        chunk_idx = lat["chunks"]
        mask_np = np.zeros((S, F), bool)
        pending_confs = np.zeros(S, np.int64)
        s = None
        while True:
            if (self.cancel_event is not None
                    and self.cancel_event.is_set()):
                self.cancelled = True
                return carry, s
            t0 = _time.monotonic()
            keep_dev = self._tier_mask_dev(mask_np)
            wd_snap = (_monitor_snapshot()
                       if getattr(self, "watchdog_factor", None)
                       else None)

            def exec_chunk(carry=carry, keep_dev=keep_dev,
                           chunk_no=chunk_no):
                if getattr(self, "mesh", None) is not None:
                    faultinject.fire("collective_seam", chunk_no,
                                     shards=self._fault_shards())
                out = tier_fn(carry, keep_dev)
                c_out, stats = out[0], out[1]
                slog = out[2] if len(out) > 2 else None
                faultinject.fire("mid_chunk", chunk_no,
                                 shards=self._fault_shards())
                td = _time.monotonic()
                return c_out, np.asarray(stats), slog, td

            # the tiered dispatch+sync runs under the same
            # hung-dispatch watchdog as the untiered chunk loop
            carry, s, shard_log, t_disp = self._guarded_dispatch(
                exec_chunk, chunk_no
            )
            t1 = _time.monotonic()
            self._note_watchdog_wall(t1 - t0, wd_snap)
            lat["chunks"] += 1
            lat["dispatch_sec"] += t_disp - t0
            fetch = t1 - t_disp
            lat["fetch_sec"] += fetch
            if lat["fetch_min"] is None or fetch < lat["fetch_min"]:
                lat["fetch_min"] = fetch
            if lat["t_first_sync"] is None:
                lat["t_first_sync"] = t1

            if tracer is not None:
                from ..memplan import device_bytes_in_use

                mem_now, src = device_bytes_in_use()
                if mem_now is not None:
                    mem_src = src
                    mem_polls += 1
                    mem_peak = (mem_now if mem_peak is None
                                else max(mem_peak, mem_now))
                waves_now = int(s[4])
                n_waves = waves_now - prev_waves
                rows = None
                if trace_log:
                    off = 11 + 3 * n_props + 3
                    rows = np.asarray(
                        s[off:off + WL]
                    ).reshape(1, WL)
                srows = self._tier_shard_rows(shard_log)
                # health layer: straggler detection (no-op unless
                # sharded + straggler_factor configured)
                self._note_shard_health(
                    None if srows is None else srows[:, :n_waves],
                    prev_waves,
                )
                tracer.record_chunk(
                    chunk=chunk_idx,
                    wave0=prev_waves,
                    t0=t0,
                    t1=t1,
                    dispatch_sec=t_disp - t0,
                    device_sec=None,
                    fetch_sec=fetch,
                    n_waves=n_waves,
                    wave_rows=(None if rows is None
                               else rows[:n_waves]),
                    pairs_valid=self._wave_log_pairs_valid(),
                    shard_rows=(None if srows is None
                                else srows[:, :n_waves]),
                    mem_bytes=mem_now,
                )
                prev_waves = waves_now
                chunk_idx += 1
                if n_props:
                    disc = s[11:11 + n_props]
                    for i, prop in enumerate(props):
                        if disc[i] and prop.name not in verdicts_seen:
                            verdicts_seen.add(prop.name)
                            tracer.event(
                                "verdict",
                                property=prop.name,
                                expectation=(
                                    prop.expectation.name.lower()
                                ),
                                kind="discovery",
                                wave=int(s[4]),
                                depth=int(s[3]),
                                chunk=chunk_idx - 1,
                            )

            done = bool(s[0])
            self._total_states = int(s[6]) | (int(s[7]) << 32)
            self._unique_states = int(s[8])
            self._max_depth = max(self._max_depth, int(s[3]))
            self.metrics = dict(
                frontier_size=int(s[5]),
                occupancy=(
                    self._unique_states / self.total_capacity
                ),
                dedup_ratio=(
                    1.0 - self._unique_states / self._total_states
                    if self._total_states else 0.0
                ),
                waves=int(s[4]),
            )
            if mem_peak is not None:
                self.metrics["device_peak_bytes"] = mem_peak

            h_np = h_np + pending_confs
            if self.track_paths:
                carry = self._tier_plog_drain(
                    carry, pl_cursor, pending_confs
                )

            overflow_msg = self._overflow_message(s)
            if overflow_msg is not None:
                if bool(s[2]):
                    overflow_msg += (
                        "  (tiered mode: the bound applies to the "
                        "wave's PROVISIONAL winners — hot-tier-new "
                        "rows before the cold membership pass — so a "
                        "frontier that fits the all-resident run may "
                        "need headroom once the hot tier spills)"
                    )
                cold.sync()
                self._consume_extra_stats(s[11 + 3 * n_props:])
                self._record_discoveries(s, props)
                if self._discovered_fps:
                    overflow_msg += (
                        "  Discoveries recorded before truncation "
                        f"(valid counterexamples): "
                        f"{sorted(self._discovered_fps)} — read them "
                        "via discovered_property_names() / "
                        "discovery_fingerprints() after catching "
                        "this error."
                    )
                self._tier_mem = (mem_peak, mem_src, mem_polls)
                if tracer is not None:
                    self._emit_memory_watermark(tracer, None, None, 0)
                raise RuntimeError(overflow_msg)

            if done:
                break

            pn, p_lo, p_hi = self._tier_pend_read(carry)
            cold.sync()
            mask_np = np.zeros((S, F), bool)
            for s_i in range(S):
                n_p = int(pn[s_i]) if s_i < pn.size else 0
                if n_p == 0:
                    continue
                member = cold.member(
                    s_i, p_lo[s_i, :n_p], p_hi[s_i, :n_p]
                )
                mask_np[s_i, :n_p] = ~member
            pending_confs = mask_np.sum(axis=1).astype(np.int64)

            if int(h_np.max()) > limit:
                carry = self._tier_spill(carry, cold, h_np)
                h_np = np.zeros(S, np.int64)

            if (self.checkpoint_every
                    and (chunk_no + 1) % self._ckpt_cadence() == 0):
                from .. import checkpoint as _ckpt

                t_ck = _time.monotonic()
                _ckpt.write_snapshot(
                    self, carry, self.checkpoint_path,
                    chunk=chunk_no, wave=int(s[4]),
                    depth=int(s[3]), unique=int(s[8]),
                    tier=cold, tier_plog=self._tier_plog_rows,
                )
                self._note_snapshot_wall(
                    _time.monotonic() - t_ck, t1 - t0
                )
            faultinject.fire("chunk_boundary", chunk_no,
                             shards=self._fault_shards())
            chunk_no += 1
            if reporter is not None:
                reporter.report_checking(
                    ReportData(
                        total_states=self._total_states,
                        unique_states=self._unique_states,
                        max_depth=self._max_depth,
                        duration_sec=self.duration_sec(),
                        done=False,
                    )
                )

        cold.sync()
        self._tier_mem = (mem_peak, mem_src, mem_polls)
        self.metrics.update(
            tier_spills=int(cold.spills),
            cold_rows=cold.rows(),
            cold_bytes=cold.bytes(),
            hot_rows=int(h_np.sum()),
        )
        return carry, s

    def _use_sparse(self) -> bool:
        if self.sparse is not None:
            return self.sparse
        return isinstance(self.encoded, SparseEncodedModel)

    def _pair_width(self) -> int:
        K = self.encoded.max_actions
        if self.pair_width is not None:
            return min(self.pair_width, K)
        hint = getattr(self.encoded, "pair_width_hint", None)
        return min(hint, K) if hint else K

    def _resolve_ample_words(self):
        """The validated host-constant ample mask (``uint32[L]``), or
        None when the filter is off. ONE home for the single-chip and
        sharded program builders — the refusals must not drift."""
        if not self.ample_set:
            return None
        from ..encoding import ample_mask_host

        enc = self.encoded
        if not self._use_sparse():
            raise ValueError(
                "ample_set requires the sparse dispatch path (the "
                "filter is an AND over the packed enabled bitmap); "
                "this run resolved to the dense wave"
            )
        aw = ample_mask_host(enc)
        if aw is None:
            raise ValueError(
                f"ample_set: encoding {type(enc).__name__} declares "
                "no ample_mask_host() — the engine cannot invent a "
                "sound reduction; declare the mask on the encoding "
                "(it owns the soundness argument) or drop the flag"
            )
        if aw.shape[0] != mask_words(enc.max_actions):
            raise ValueError(
                f"ample_mask_host() returned {aw.shape[0]} words; "
                f"max_actions={enc.max_actions} needs "
                f"{mask_words(enc.max_actions)}"
            )
        # the certificate gate (analysis/soundness.py): the declared
        # mask only filters once enabledness-preservation and
        # non-suppression are discharged — an uncertifiable mask
        # refuses with the failed obligation unless --unsound-ok.
        from ..analysis.soundness import gate_ample

        gate_ample(enc, self._engine_name, self.unsound_ok)
        return np.asarray(aw, np.uint32)

    def _cache_extras(self) -> tuple:
        return (
            "sortmerge",
            self.tiles,
            self.tile_rows,
            self.f_min,
            self.v_min,
            self.ladder_step,
            self.v_ladder_step,
            self.flat_budget_bytes,
            self._use_sparse(),
            self._pair_width(),
            self.mask_budget_cells,
            # the visited-dedup implementation changes the compiled
            # wave program (Pallas kernel vs XLA fallback).
            self.merge_impl,
            # traced runs carry the wave log: a different program.
            self._wave_log_enabled(),
            # device symmetry / ample-set change the compiled wave
            # programs (canonicalization pass, enabled-bits AND) for
            # the SAME encoding, so they key the program cache too.
            self.sym_spec is not None,
            self.ample_set,
        )

    # -- telemetry (stateright_tpu/telemetry.py) ---------------------------
    #
    # _wave_log_enabled is inherited from TpuBfsChecker (one home for
    # the tracer→program gate; the sharded engines key their per-shard
    # mesh log on the same flag).

    def _wave_log_rows(self, s: np.ndarray, n_props: int):
        if not self._wave_log_enabled():
            return None
        from ..telemetry import WAVE_LOG_LANES as WL

        off = 11 + 3 * n_props + 3  # scalars + discovery + peak lanes
        return s[off:off + self.waves_per_sync * WL].reshape(
            self.waves_per_sync, WL
        )

    def _lane_config(self) -> dict:
        lane = super()._lane_config()
        lane.update(
            sparse=self._use_sparse(),
            pair_width=(self._pair_width() if self._use_sparse()
                        else None),
            auto_budget=self.auto_budget,
            tiles=self.tiles,
            tile_rows=self.tile_rows,
            f_min=self.f_min,
            v_min=self.v_min,
            ladder_step=self.ladder_step,
            v_ladder_step=self.v_ladder_step,
            flat_budget_bytes=self.flat_budget_bytes,
            mask_budget_cells=self.mask_budget_cells,
            merge_impl=self.merge_impl,
            tier_hot_rows=self.tier_hot_rows,
            symmetry=self.sym_spec is not None,
            ample_set=self.ample_set,
        )
        if self.sym_spec is not None or self.ample_set:
            # certificate provenance rides the lane config (and hence
            # every trace's run_begin record): True/False when the
            # analyzer ran, absent when no reduction is on.
            from ..analysis.soundness import soundness_status

            lane.update(
                soundness_certified=soundness_status(self.encoded)
            )
        return lane

    def _maybe_warn_occupancy(self, occupancy: float) -> None:
        """No probe pressure: the sorted array works at 100% occupancy
        and overflow is detected exactly — nothing to warn about
        per-chunk. (Per-SHARD occupancy headroom on the mesh engines
        IS watched, by the trace-side metric: telemetry.shard_balance
        reuses the shared formatter in stateright_tpu/occupancy.py
        with the exact-capacity HEADROOM_THRESHOLD.)"""

    # -- memory observability (stateright_tpu/memplan.py) ------------------

    def _visited_bytes_per_row(self) -> int:
        # vkeys: two uint32 key limbs; plog appends 4 uint32 lanes
        # (parent + child limbs) per unique state when paths are on.
        return 8 + (16 if self.track_paths else 0)

    def _budget_headroom(self):
        """The observed-peak-vs-budget join the watermark carries:
        the same persisted store the auto-budget sizes from, so the
        headroom the event reports is the headroom the next process's
        budget decision reads."""
        if not self.auto_budget:
            return None
        peak = self.metrics.get("max_wave_candidates")
        if not peak:
            saved = self._load_budget() or {}
            peak = saved.get("observed_peak")
        cap = self.cand_capacity
        return dict(
            cand_capacity=cap,
            observed_peak=(int(peak) if peak else None),
            headroom_ratio=(round(cap / peak, 4)
                            if cap and peak else None),
        )

    def _memory_projection(self) -> dict:
        """Predicted resident bytes at the NEXT visited ladder class
        — the number that decides when V stops fitting VMEM (ROADMAP
        direction 2b) and when the visited set must tier to host DRAM
        (direction 1b). Past the ladder top the projection prices the
        next capacity step (``capacity * v_ladder_step``) instead: the
        cost of the next-size workload."""
        v_ladder = _ladder(self.v_min, self.capacity,
                           self.v_ladder_step)
        shards = getattr(self, "n_shards", 1)
        u_shard = -(-max(self._unique_states, 1) // shards)
        # the class the engine dispatched at the end of the run (the
        # same u > V_i counting the wave body's ladder switch uses),
        # and the step PAST it — past the ladder top, the next
        # capacity a bigger workload would need
        idx = sum(1 for V_i in v_ladder[:-1] if u_shard > V_i)
        cur = v_ladder[idx]
        nxt = (v_ladder[idx + 1] if idx + 1 < len(v_ladder)
               else self.capacity * self.v_ladder_step)
        F = self.frontier_capacity
        return dict(
            kind="next_v_class",
            current_rows=int(cur),
            next_rows=int(nxt),
            # vkeys [2, V + F]: the resident block the class keeps
            next_vkeys_bytes=int((nxt + F) * 8),
            # the streaming merge reads [0, V) and writes the merged
            # [0, V + NF) block back: the class-local scratch
            next_merge_scratch_bytes=int((nxt + F) * 8),
        )

    def _cand_overflow_message(self) -> str:
        if self._use_sparse():
            return (
                "pair-buffer overflow: a wave enabled more (row, slot) "
                f"pairs than cand_capacity={self.cand_capacity}, or one "
                f"row enabled more than pair_width={self._pair_width()} "
                "slots; raise the exceeded knob — the "
                "max_wave_candidates metric reports the observed peak"
            )
        return (
            "candidate-buffer overflow: a wave generated more valid "
            f"successors than cand_capacity={self.cand_capacity} (or, on "
            "the per-tile-payload fallback path, one tile exceeded its "
            "slice of that budget); re-run with a larger cand_capacity — "
            "the max_wave_candidates metric reports the observed peak"
        )

    # -- device programs ---------------------------------------------------

    def _build_programs(self, n0: int, tiered: bool = False):
        """``tiered=False`` (the default) builds the untiered
        seed/chunk pair — byte-identical to every round since 10.
        ``tiered=True`` builds the TIERED chunk program
        (stateright_tpu/tier.py): one wave per dispatch, whose carry
        additionally stages the wave's provisional winners
        (``pend_keys``/``pend_par``/``pend_n``) and whose entry phase
        COMMITS the previous wave's survivors under the host's
        cold-membership ``keep`` mask — count, frontier, parent log,
        and the hot-tier merge see exactly the truly-new rows, in the
        same key-sorted order the untiered engine commits."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        tier_mode = bool(tiered)
        enc = self.encoded
        # Device symmetry reduction (ops/canonical.py): when the
        # builder asked for symmetry, __init__ resolved the encoding's
        # DeviceRewriteSpec (or refused loudly). Every fingerprint
        # site below folds the CANONICAL block; the frontier keeps the
        # concrete states — the visited-through-representatives /
        # search-through-originals split of the host DFS.
        sym = self.sym_spec
        if sym is not None:
            from ..ops.canonical import canonicalize_rows, canonicalize_t
        ample_words = self._resolve_ample_words()
        props = list(self.model.properties())
        n_props = len(props)
        evt_idx = [
            i for i, p in enumerate(props)
            if p.expectation == Expectation.EVENTUALLY
        ]
        if evt_idx and max(evt_idx) >= 32:
            raise ValueError(
                "the TPU engine supports eventually properties only at "
                "property indices < 32; reorder properties() so eventually "
                f"properties come first (got index {max(evt_idx)})"
            )
        # XLA:CPU needs a gather-arrangement workaround in the sparse
        # fetch (see the pay_fetch branches below).
        cpu_backend = jax.default_backend() == "cpu"
        K, W, F = enc.max_actions, enc.width, self.frontier_capacity
        C = self.capacity
        B_user = min(self.cand_capacity or F * K, F * K)
        target_states = self.builder._target_state_count
        target_depth = self.builder._target_max_depth
        waves_per_sync = self.waves_per_sync
        ebits_init = self._eventually_bits_init()
        track_paths = self.track_paths
        # Per-wave trace log (telemetry.py): when a tracer is active
        # the carry gains a small uint32[waves_per_sync, WL] log the
        # wave body appends one row to, downloaded WITH the packed
        # stats — one readback per chunk, async dispatch preserved.
        # Gated (and cache-keyed, _cache_extras) so untraced runs
        # compile the exact programs they always did.
        from ..telemetry import WAVE_LOG_LANES as WL

        trace_log = self._wave_log_enabled()
        # Parent log rows: every unique state (≤ C) gets one entry;
        # the F-row block write at a dynamic offset needs headroom.
        L = C + F if track_paths else 0

        # Ladder bottoms are deliberately coarse (waves below f_min
        # frontier rows are dispatch/sync-dominated, merges below v_min
        # cost single-digit ms) and the visited ladder is coarser than
        # the frontier ladder: XLA compile time grows superlinearly in
        # the number of (f, v) branch combinations, and each visited
        # step only changes merge-sort row counts.
        f_ladder = _ladder(self.f_min, F, self.ladder_step)
        v_ladder = _ladder(self.v_min, C, self.v_ladder_step)

        def clamp_keys(lo, hi):
            # All-ones is the visited-array padding sentinel; nudge
            # real fingerprints off it (mirrors the NonZero convention
            # at the other end of the range, ops/fingerprint.py).
            both = (lo == jnp.uint32(_SENT)) & (hi == jnp.uint32(_SENT))
            return lo, jnp.where(both, jnp.uint32(_SENT - 1), hi)

        # The visited array is INCREMENTALLY SORTED (round 10): rows
        # [0, u) are a dense sorted run of real keys, [u, C_pad) all-
        # ones sentinels. Each wave's merge_stage linear-merges the
        # ≤F sorted winner keys into the prefix (ops/merge.py) — the
        # invariant every streaming pass (membership, append) depends
        # on, and the one the module docstring's "sorted fingerprint
        # array" line has described since round 2. The F rows of
        # headroom let the class-local [0, V_v + NF) merged-block
        # write land inside the buffer even at V_v == C.
        C_pad = C + F

        def seed(init_rows):
            # Host upload boundary: init states arrive row-major and
            # transpose ONCE into the [W, F] resident layout (PERF.md
            # §layout — boundary transposes live here and at the
            # gather seams only).
            # Canonical visited keys from wave zero: the init rows
            # fingerprint through their orbit representatives, same
            # as every candidate wave below (the frontier still
            # stores the concrete init states).
            fp_rows = (canonicalize_rows(sym, init_rows, jnp)
                       if sym is not None else init_rows)
            lo0, hi0 = fingerprint_u32v(fp_rows, jnp)
            lo0, hi0 = clamp_keys(lo0, hi0)
            # Seed the SORTED invariant: the init keys are the first
            # visited prefix, so they go in (hi, lo)-ordered (an
            # n0-row sort, once, at upload).
            hi0, lo0 = lax.sort((hi0, lo0), num_keys=2)
            vkeys = (
                jnp.full((2, C_pad), _SENT, jnp.uint32)
                .at[0, :n0].set(lo0)
                .at[1, :n0].set(hi0)
            )
            frontier = jnp.zeros((W, F), dtype=jnp.uint32).at[
                :, :n0
            ].set(init_rows.T)
            fval = jnp.arange(F) < n0
            ebits = jnp.where(fval, jnp.uint32(ebits_init), jnp.uint32(0))
            extra = (
                dict(
                    wlog=jnp.zeros((waves_per_sync, WL), jnp.uint32),
                    wv_pairs=jnp.uint32(0),
                    wv_canon=jnp.uint32(0),
                )
                if trace_log
                else {}
            )
            return dict(
                vkeys=vkeys,
                **extra,
                plog=jnp.zeros((4, L), jnp.uint32),
                pl_n=jnp.uint32(0),
                frontier=frontier,
                fval=fval,
                ebits=ebits,
                n_frontier=jnp.uint32(n0),
                depth=jnp.int32(1),
                wchunk=jnp.int32(0),
                waves=jnp.uint32(0),
                gen_lo=jnp.uint32(n0),
                gen_hi=jnp.uint32(0),
                new=jnp.uint32(n0),
                disc_found=jnp.zeros(n_props, dtype=bool),
                disc_lo=jnp.zeros(n_props, dtype=jnp.uint32),
                disc_hi=jnp.zeros(n_props, dtype=jnp.uint32),
                overflow=jnp.bool_(n0 > C),
                f_overflow=jnp.bool_(False),
                c_overflow=jnp.bool_(False),
                e_overflow=jnp.bool_(False),
                max_cand=jnp.uint32(0),
                max_tile_cand=jnp.uint32(0),
                max_rowen=jnp.uint32(0),
                done=jnp.bool_(n0 == 0),
            )

        def class_params(fc: int):
            """Static per-frontier-class shapes."""
            F_f = f_ladder[fc]
            FK = F_f * K
            B_class = min(FK, B_user)
            compaction = FK > B_class
            want_tiles = -(-FK // self.tile_rows)
            if F_f == F:
                want_tiles = max(want_tiles, self.tiles)
            NT = _divisor_at_least(F_f, want_tiles)
            T = F_f // NT
            # Non-full-flat (per-tile payload) path: per-tile budget
            # gets slack over the even split (25% plus a floor) —
            # candidates skew across tiles. Capped at the lossless T*K.
            Bt = -(-B_class // NT)
            if NT > 1:
                Bt += max(8192, Bt // 4)
            Bt = min(Bt, T * K)
            B_eff = Bt * NT
            # Full-flat path: packed tile append needs one tile of
            # headroom past the whole-wave budget and has NO per-tile
            # overflow mode.
            Ba = (B_class + T * K) if compaction else FK
            full_flat = FK * W * 4 <= self.flat_budget_bytes
            return (F_f, FK, NT, T, Bt, B_eff, Ba, B_class, compaction,
                    full_flat)

        def merge_stage(c, v_class, B_eff, ck_lo, ck_hi, fetch, n_cand,
                        disc_found, disc_lo, disc_hi, c_overflow,
                        e_overflow, max_tile_cand, max_rowen=None,
                        wv_pairs=None, wv_canon=None):
            """The streaming-merge dedup (round 10, PERF.md
            §merge-kernel), class-collapsed per round 9: no switch
            branch ever returns more than one resident buffer.

            * ONE stable 3-lane ``B_eff``-row sort orders the wave's
              candidates by key with the buffer position as the value
              lane — the only per-wave sort whose row count exceeds
              the winner block, and it is B-scale: the ``(V_v +
              B)``-row concat sort this stage ran through round 9 is
              gone (the b·V term). Stability keeps equal keys in
              buffer order, so the adjacent-equal check makes the
              lowest-position candidate the intra-wave winner —
              exactly the old stable-concat-sort winner;
            * the v-ladder switch runs the MEMBERSHIP pass against
              the sorted visited prefix (``ops.merge.member_sorted``:
              the Pallas streaming kernel or the binary-search XLA
              fallback, per ``merge_impl``); its only branch output
              is the ``bool[B_eff]`` mask;
            * winners — in KEY order, which IS their order in the
              sorted candidate array — come out of one order-
              preserving 4-lane compaction sort (B-scale), yielding
              ``nf_pos`` (buffer positions, for the fetch gather) and
              the sorted winner keys the visited merge consumes;
            * ONE fetch-class switch per wave (the third ladder axis,
              sized to this wave's new_count) gathers the winners and
              updates frontier, ebits, and ``plog`` with class-local
              ``dynamic_update_slice`` blocks; rows past the block
              keep stale values, which ``fval`` masks everywhere;
            * a second v-class switch linear-merges the winner keys
              into ``vkeys`` (``ops.merge.merge_sorted`` + one
              class-local block write — no O(C)-row sort); its only
              branch output is the updated ``vkeys``;
            * the next carry is assembled OUTSIDE any switch.

            ``fetch(nf_row)`` returns ``(state_cols[W, n], par_lo,
            par_hi, row_ebits, key_lo, key_hi)`` — winner states come
            back COLUMN-major, matching the ``[W, F]`` resident
            frontier's block write (the recompute fetch produces this
            natively; gather-seam fetches transpose their row-major
            winner block once, the sanctioned seam copy). The keys
            still ride the SAME packed gather as the payload (PERF.md
            §gathers: one multi-lane gather, never N scalar
            gathers); with the fetch order now key-sorted they land
            in ``plog``'s child lanes ascending, same values the
            visited merge gets from the compaction sort."""
            NF = min(F, B_eff)

            cpos = jnp.arange(1, B_eff + 1, dtype=jnp.uint32)
            s_hi, s_lo, s_pos = lax.sort(
                (ck_hi, ck_lo, cpos), num_keys=2
            )
            real = ~(
                (s_hi == jnp.uint32(_SENT))
                & (s_lo == jnp.uint32(_SENT))
            )
            prev_same = jnp.concatenate(
                [
                    jnp.zeros(1, bool),
                    (s_hi[1:] == s_hi[:-1])
                    & (s_lo[1:] == s_lo[:-1]),
                ]
            )
            fresh = real & ~prev_same

            def member_core(vc):
                V_v = v_ladder[vc]

                def br(_):
                    return member_sorted(
                        c["vkeys"][0, :V_v], c["vkeys"][1, :V_v],
                        s_lo, s_hi, impl=self.merge_impl,
                    )

                return br

            in_visited = lax.switch(
                v_class,
                [member_core(vc) for vc in range(len(v_ladder))],
                0,
            )
            is_new = fresh & ~in_visited
            new_count = jnp.sum(is_new)
            # Order-preserving winner compaction (ops/merge.py,
            # impl-adaptive: O(B) rank scatter on the XLA fallback,
            # one 4-lane B-scale sort on the Pallas/TPU path):
            # winners lead in key order, the order every consumer now
            # shares (fetch block, plog append, visited merge).
            nf_pos, w_lo, w_hi = compact_winners(
                is_new, s_pos, s_lo, s_hi, NF, impl=self.merge_impl
            )

            if tier_mode:
                # the commit phase (next dispatch) owns the visited-
                # capacity check against the HOT count; the cumulative
                # unique count may legitimately exceed device capacity
                overflow = c["overflow"]
            else:
                overflow = c["overflow"] | (
                    c["new"] + new_count.astype(jnp.uint32)
                    > jnp.uint32(C)
                )
            f_overflow = c["f_overflow"] | (new_count > F)

            # Fetch width: the payload gather is the merge's costliest
            # op at big shapes (paxos-5: a static min(F, B_eff)=1.57M-
            # row gather cost ~62ms/wave while typical waves produced
            # ~120k new states), so the fetch runs under its own class
            # switch sized to THIS wave's new_count.
            nf_ladder = [n for n in f_ladder if n < NF] + [NF]
            nf_class = jnp.int32(0)
            for n in nf_ladder[:-1]:
                nf_class = nf_class + (new_count > n).astype(jnp.int32)

            def make_fetch(NF_c):
                def br(_):
                    pos = nf_pos[:NF_c]
                    valid = jnp.arange(NF_c) < new_count
                    nf_row = jnp.where(valid, pos - 1, jnp.uint32(0))
                    (state_cols, par_lo, par_hi, row_ebits,
                     key_lo, key_hi) = fetch(nf_row)
                    z = jnp.uint32(0)
                    frontier2 = lax.dynamic_update_slice(
                        c["frontier"],
                        jnp.where(valid[None, :], state_cols,
                                  jnp.uint32(0)),
                        (z, z),
                    )
                    ebits2 = lax.dynamic_update_slice(
                        c["ebits"],
                        jnp.where(valid, row_ebits, 0),
                        (z,),
                    )
                    # Parent-log append: parent AND child limbs —
                    # the sorted visited merge re-orders vkeys rows
                    # every wave, so the round-9 derive-children-
                    # from-vkeys drain no longer has an insertion
                    # order to read; the log carries the child keys
                    # again (lanes 2-3), in the same key-sorted
                    # fetch order as the parents (_build_generated).
                    if not track_paths:
                        plog2 = c["plog"]
                    elif tier_mode:
                        # stage the parent limbs beside the staged
                        # states — the commit appends the SURVIVORS
                        # to the parent log, so no false-new row ever
                        # reaches the drain
                        plog2 = lax.dynamic_update_slice(
                            c["pend_par"],
                            jnp.stack([
                                jnp.where(valid, par_lo, 0),
                                jnp.where(valid, par_hi, 0),
                            ]),
                            (z, z),
                        )
                    else:
                        plog2 = lax.dynamic_update_slice(
                            c["plog"],
                            jnp.stack([
                                jnp.where(valid, par_lo, 0),
                                jnp.where(valid, par_hi, 0),
                                jnp.where(valid, key_lo, 0),
                                jnp.where(valid, key_hi, 0),
                            ]),
                            (z, c["pl_n"]),
                        )
                    return frontier2, ebits2, plog2

                return br

            next_frontier, next_ebits, plog_new = lax.switch(
                nf_class,
                [make_fetch(n) for n in nf_ladder],
                0,
            )

            # Visited append: linear-merge the sorted winner block
            # into the sorted prefix and write the merged run back as
            # ONE class-local block at offset 0 (rows past V_v + NF
            # are untouched sentinels by the C_pad headroom). The
            # branch output is vkeys alone — the same single-resident-
            # buffer switch discipline as the fetch switch above.
            def append_core(vc):
                V_v = v_ladder[vc]

                def br(_):
                    m_lo, m_hi = merge_sorted(
                        c["vkeys"][0, :V_v], c["vkeys"][1, :V_v],
                        w_lo, w_hi, impl=self.merge_impl,
                    )
                    return lax.dynamic_update_slice(
                        c["vkeys"],
                        jnp.stack([m_lo, m_hi]),
                        (jnp.uint32(0), jnp.uint32(0)),
                    )

                return br

            if tier_mode:
                vkeys_new = c["vkeys"]  # the commit phase merges
            else:
                vkeys_new = lax.switch(
                    v_class,
                    [append_core(vc) for vc in range(len(v_ladder))],
                    0,
                )

            nf_valid_f = jnp.arange(F) < new_count
            if track_paths and not tier_mode:
                # Clamp to the NF rows the largest block write can
                # hold: on an f_overflow wave new_count can exceed
                # it, and _run raises before reconstruction — but
                # the live-count invariant should hold regardless.
                pl_n = c["pl_n"] + jnp.minimum(
                    new_count.astype(jnp.uint32), jnp.uint32(NF)
                )
            else:
                pl_n = c["pl_n"]

            g = u64_add(
                U64(c["gen_lo"], c["gen_hi"]),
                U64(n_cand.astype(jnp.uint32), jnp.uint32(0)),
            )
            if tier_mode:
                # DEFERRED COMMIT (stateright_tpu/tier.py): stage the
                # provisional winners — sorted keys here, states/ebits
                # already written into the frontier staging by the
                # fetch switch, parent limbs in pend_par — and leave
                # vkeys, the parent log, and every committed counter
                # untouched. compact_winners sentinel-pads past
                # new_count, so the staged key block is (hi, lo)-
                # sorted with a sentinel tail, exactly what the
                # commit's merge consumes.
                nc32 = new_count.astype(jnp.uint32)
                pk_lo = lax.dynamic_update_slice(
                    jnp.full(F, _SENT, jnp.uint32), w_lo[:NF], (0,)
                )
                pk_hi = lax.dynamic_update_slice(
                    jnp.full(F, _SENT, jnp.uint32), w_hi[:NF], (0,)
                )
                trace_extra = {}
                if trace_log:
                    trace_extra = dict(
                        wlog=c["wlog"],
                        pstash=c["pstash"],
                        wv_pairs=(n_cand if wv_pairs is None
                                  else wv_pairs).astype(jnp.uint32),
                        wv_canon=(jnp.uint32(0) if wv_canon is None
                                  else wv_canon.astype(jnp.uint32)),
                    )
                return dict(
                    **trace_extra,
                    **(dict(pend_par=plog_new) if track_paths
                       else {}),
                    vkeys=c["vkeys"],
                    plog=c["plog"],
                    pl_n=c["pl_n"],
                    frontier=next_frontier,
                    fval=nf_valid_f,
                    ebits=next_ebits,
                    n_frontier=nc32,
                    n_hot=c["n_hot"],
                    pend_keys=jnp.stack([pk_lo, pk_hi]),
                    pend_n=nc32,
                    pend_valid=jnp.bool_(True),
                    depth=c["depth"],
                    wchunk=c["wchunk"] + 1,
                    waves=c["waves"],
                    gen_lo=g.lo,
                    gen_hi=g.hi,
                    new=c["new"],
                    disc_found=disc_found,
                    disc_lo=disc_lo,
                    disc_hi=disc_hi,
                    overflow=overflow,
                    f_overflow=f_overflow,
                    c_overflow=c_overflow,
                    e_overflow=e_overflow,
                    max_cand=jnp.maximum(c["max_cand"], n_cand),
                    max_tile_cand=max_tile_cand,
                    max_rowen=(c["max_rowen"] if max_rowen is None
                               else max_rowen),
                    done=c["done"],
                )
            new = c["new"] + new_count.astype(jnp.uint32)
            all_disc = (
                jnp.all(disc_found) if n_props else jnp.bool_(False)
            )
            if target_states is None:
                target_hit = jnp.bool_(False)
            else:
                target_hit = new >= jnp.uint32(target_states)
            cont = (
                (new_count > 0)
                & ~all_disc
                & ~target_hit
                & ~overflow
                & ~f_overflow
                & ~c_overflow
                & ~e_overflow
            )
            trace_extra = {}
            if trace_log:
                # The wave log never crosses a switch boundary now —
                # it rides only the assembled carry; the body wrapper
                # writes this wave's row after the f-switch returns.
                trace_extra = dict(
                    wlog=c["wlog"],
                    wv_pairs=(n_cand if wv_pairs is None
                              else wv_pairs).astype(jnp.uint32),
                    wv_canon=(jnp.uint32(0) if wv_canon is None
                              else wv_canon.astype(jnp.uint32)),
                )
            return dict(
                **trace_extra,
                vkeys=vkeys_new,
                plog=plog_new,
                pl_n=pl_n,
                frontier=next_frontier,
                fval=nf_valid_f & cont,
                ebits=next_ebits,
                # The true row count even when the run stops (the
                # wave loop gates on done/fval, so this is safe) —
                # frontier rows past the class-local block are
                # STALE now, so tooling that reruns stages on a
                # captured carry (tools/profile_stages.py) reads
                # the live-row count here instead of scanning for
                # zero rows.
                n_frontier=new_count.astype(jnp.uint32),
                depth=jnp.where(cont, c["depth"] + 1, c["depth"]),
                wchunk=c["wchunk"] + 1,
                waves=c["waves"] + 1,
                gen_lo=g.lo,
                gen_hi=g.hi,
                new=new,
                disc_found=disc_found,
                disc_lo=disc_lo,
                disc_hi=disc_hi,
                overflow=overflow,
                f_overflow=f_overflow,
                c_overflow=c_overflow,
                e_overflow=e_overflow,
                max_cand=jnp.maximum(c["max_cand"], n_cand),
                max_tile_cand=max_tile_cand,
                max_rowen=(c["max_rowen"] if max_rowen is None
                           else max_rowen),
                done=~cont,
            )

        def make_wave(fc: int, v_class):
            (
                F_f, FK, NT, T, Bt, B_eff, Ba, B_class, compaction,
                full_flat,
            ) = class_params(fc)

            def wave(c):
                if target_depth is None:
                    expand = jnp.bool_(True)
                else:
                    expand = c["depth"] < target_depth

                # Dense expansion runs step_vec on state ROWS; the
                # resident frontier is [W, F], so the dense path pays
                # one seam transpose of its class prefix per wave
                # (the sparse path — the default for every registered
                # encoding — stays transpose-free up to the pair-step
                # gather seam).
                frontier_rows = c["frontier"][:, :F_f].T
                fval_f = c["fval"][:F_f]
                ebits_f = c["ebits"][:F_f]

                if full_flat:
                    # Expand the whole class prefix at once; the F_f*K
                    # successor tensor stays alive through the merge so
                    # only the ≤F winning rows are ever gathered.
                    ex = expand_frontier(
                        enc, props, evt_idx, frontier_rows, fval_f,
                        ebits_f, expand, with_repeats=False,
                        sym_spec=sym,
                    )
                    e_overflow = c["e_overflow"] | jnp.any(ex["trunc"])
                    disc_found, disc_lo, disc_hi = discovery_update(
                        props, ex, fval_f,
                        c["disc_found"], c["disc_lo"], c["disc_hi"],
                    )
                    flat, valid = ex["flat"], ex["v"]
                    wv_canon = None
                    if sym is not None:
                        cflat = canonicalize_rows(sym, flat, jnp)
                        k_lo, k_hi = fingerprint_u32v(cflat, jnp)
                        if trace_log:
                            wv_canon = jnp.sum(
                                valid & (cflat != flat).any(axis=1),
                                dtype=jnp.uint32,
                            )
                    else:
                        k_lo, k_hi = fingerprint_u32v(flat, jnp)
                    k_lo, k_hi = clamp_keys(k_lo, k_hi)
                    k_lo = jnp.where(valid, k_lo, jnp.uint32(_SENT))
                    k_hi = jnp.where(valid, k_hi, jnp.uint32(_SENT))
                    n_cand = jnp.sum(valid).astype(jnp.uint32)
                    if compaction:
                        # Tiled key compaction via PACKED APPEND: each
                        # tile sorts its keys (sort is superlinear: NT
                        # small sorts beat one big one; sentinel keys
                        # sort last, so valid rows lead) and writes its
                        # FULL sorted block at the running valid-count
                        # offset. Successive contiguous writes overlap
                        # the previous tile's sentinel tail, so valid
                        # candidates pack densely and no per-tile
                        # budget exists to overflow — only the
                        # whole-wave cand_capacity contract remains.
                        def tile_body(t, acc):
                            ck_lo, ck_hi, crow, app_off, tmax = acc
                            off = t * (T * K)
                            t_lo = lax.dynamic_slice(k_lo, (off,), (T * K,))
                            t_hi = lax.dynamic_slice(k_hi, (off,), (T * K,))
                            t_vd = lax.dynamic_slice(
                                valid, (off,), (T * K,)
                            )
                            rows = off.astype(jnp.uint32) + jnp.arange(
                                T * K, dtype=jnp.uint32
                            )
                            tc = jnp.sum(t_vd).astype(jnp.uint32)
                            tmax = jnp.maximum(tmax, tc)
                            s_hi, s_lo, s_row = lax.sort(
                                (t_hi, t_lo, rows), num_keys=2
                            )
                            o = (app_off,)
                            ck_lo = lax.dynamic_update_slice(ck_lo, s_lo, o)
                            ck_hi = lax.dynamic_update_slice(ck_hi, s_hi, o)
                            crow = lax.dynamic_update_slice(crow, s_row, o)
                            return ck_lo, ck_hi, crow, app_off + tc, tmax

                        ck_lo, ck_hi, crow, _app_off, tile_max = (
                            lax.fori_loop(
                                0,
                                NT,
                                tile_body,
                                (
                                    jnp.full(Ba, _SENT, jnp.uint32),
                                    jnp.full(Ba, _SENT, jnp.uint32),
                                    jnp.zeros(Ba, jnp.uint32),
                                    jnp.uint32(0),
                                    jnp.uint32(0),
                                ),
                            )
                        )
                        c_overflow = c["c_overflow"] | (
                            n_cand > jnp.uint32(B_class)
                        )
                    else:
                        ck_lo, ck_hi = k_lo, k_hi
                        crow = jnp.arange(FK, dtype=jnp.uint32)
                        c_overflow = c["c_overflow"]
                        tile_max = n_cand

                    # Packed fetch (PERF.md §gathers): candidate meta
                    # (key limbs + source row) rides ONE 3-lane gather;
                    # frontier-side meta (ebits + parent fp) another.
                    meta3 = jnp.stack([ck_lo, ck_hi, crow], axis=1)
                    fr_meta = jnp.stack(
                        [ex["ebits"]]
                        + ([ex["f_lo"], ex["f_hi"]] if track_paths
                           else []),
                        axis=1,
                    )

                    def fetch(nf_row):
                        m = meta3[nf_row]
                        srow = m[:, 2]
                        q = fr_meta[srow // jnp.uint32(K)]
                        return (
                            # gather seam: winners come off the row-
                            # major flat tensor; one small [n, W]
                            # transpose feeds the [W, F] block write.
                            flat[srow].T,
                            q[:, 1] if track_paths else None,
                            q[:, 2] if track_paths else None,
                            q[:, 0],
                            m[:, 0],
                            m[:, 1],
                        )

                    return merge_stage(
                        c, v_class, Ba, ck_lo, ck_hi, fetch,
                        n_cand, disc_found, disc_lo, disc_hi,
                        c_overflow, e_overflow,
                        jnp.maximum(c["max_tile_cand"], tile_max),
                        wv_canon=wv_canon,
                    )

                # Per-tile payload path (successor tensor too big to
                # keep): expansion, fingerprinting, compaction, and a
                # Bt-row payload gather all happen inside each tile.
                # Payload lanes are PACKED into one [B_eff, EP] buffer
                # (payload_pack layout) so the merge fetch is a single
                # multi-lane gather (PERF.md §gathers); the key limbs
                # are kept as separate 1-D arrays too — the merge sort
                # concatenates those.
                EP = payload_width(W, track_paths)

                def tile_body(t, acc):
                    (
                        ck_lo, ck_hi, cpay,
                        dfound, dlo, dhi, n_cand, c_ovf, e_ovf, tmax,
                    ) = acc
                    off = t * T
                    tf = lax.dynamic_slice(
                        frontier_rows, (off, 0), (T, W)
                    )
                    tfv = lax.dynamic_slice(c["fval"], (off,), (T,))
                    teb = lax.dynamic_slice(c["ebits"], (off,), (T,))
                    ex = expand_frontier(
                        enc, props, evt_idx, tf, tfv, teb, expand,
                        with_repeats=False, sym_spec=sym,
                    )
                    e_ovf = e_ovf | jnp.any(ex["trunc"])
                    dfound, dlo, dhi = discovery_update(
                        props, ex, tfv, dfound, dlo, dhi
                    )
                    flat, valid = ex["flat"], ex["v"]
                    # Canonical keys; the payload keeps the CONCRETE
                    # successor rows (the hits lane rides the sparse
                    # and full-flat paths only — this fallback path
                    # reports wv_canon=0).
                    fp_flat = (canonicalize_rows(sym, flat, jnp)
                               if sym is not None else flat)
                    k_lo, k_hi = fingerprint_u32v(fp_flat, jnp)
                    k_lo, k_hi = clamp_keys(k_lo, k_hi)
                    k_lo = jnp.where(valid, k_lo, jnp.uint32(_SENT))
                    k_hi = jnp.where(valid, k_hi, jnp.uint32(_SENT))
                    t_cand = jnp.sum(valid)
                    tmax = jnp.maximum(tmax, t_cand.astype(jnp.uint32))
                    c_ovf = c_ovf | (t_cand > Bt)
                    rows = jnp.arange(T * K, dtype=jnp.uint32)
                    s_hi, s_lo, s_row = lax.sort(
                        (k_hi, k_lo, rows), num_keys=2
                    )
                    s_hi, s_lo, s_row = s_hi[:Bt], s_lo[:Bt], s_row[:Bt]
                    prow = s_row // jnp.uint32(K)
                    blk = payload_pack(
                        jnp, flat[s_row], s_lo, s_hi,
                        ex["ebits"][prow],
                        ex["f_lo"][prow] if track_paths else None,
                        ex["f_hi"][prow] if track_paths else None,
                    )
                    o = t * Bt
                    ck_lo = lax.dynamic_update_slice(ck_lo, s_lo, (o,))
                    ck_hi = lax.dynamic_update_slice(ck_hi, s_hi, (o,))
                    cpay = lax.dynamic_update_slice(cpay, blk, (o, 0))
                    return (
                        ck_lo, ck_hi, cpay,
                        dfound, dlo, dhi,
                        n_cand + t_cand.astype(jnp.uint32), c_ovf, e_ovf,
                        tmax,
                    )

                (
                    ck_lo, ck_hi, b_pay,
                    disc_found, disc_lo, disc_hi, n_cand, c_overflow,
                    e_overflow, tile_max,
                ) = lax.fori_loop(
                    0,
                    NT,
                    tile_body,
                    (
                        jnp.full(B_eff, _SENT, jnp.uint32),
                        jnp.full(B_eff, _SENT, jnp.uint32),
                        jnp.zeros((B_eff, EP), jnp.uint32),
                        c["disc_found"],
                        c["disc_lo"],
                        c["disc_hi"],
                        jnp.uint32(0),
                        c["c_overflow"],
                        c["e_overflow"],
                        jnp.uint32(0),
                    ),
                )

                def fetch(nf_row):
                    st, p_lo, p_hi, eb_w, k_lo_w, k_hi_w = (
                        payload_unpack(b_pay[nf_row], W, track_paths)
                    )
                    # gather seam: one [n, W] winner-block transpose.
                    return st.T, p_lo, p_hi, eb_w, k_lo_w, k_hi_w

                return merge_stage(
                    c, v_class, B_eff, ck_lo, ck_hi, fetch,
                    n_cand, disc_found, disc_lo, disc_hi,
                    c_overflow, e_overflow,
                    jnp.maximum(c["max_tile_cand"], tile_max),
                )

            return wave

        # -- sparse action dispatch (PERF.md §paxos) ---------------------
        #
        # The dense wave pays O(F·K) successor construction,
        # fingerprints and compaction sorts even when only a sliver of
        # the K slots is enabled (paxos check 3: ~200x padding). The
        # sparse wave instead:
        #   1. evaluates the encoding's CHEAP per-slot enabled
        #      predicate over [F, K] (field extracts, no successors),
        #   2. packs it to per-row bitmaps and peels up to pair_width
        #      enabled slots per row with a lowest-set-bit loop —
        #      elementwise passes over [F, K/32] lanes, no sort,
        #   3. compacts the (row, slot) pairs with tiled 1-lane
        #      packed-append sorts over the F×pair_width grid (a
        #      K/pair_width-times smaller sort than the dense path's),
        #   4. runs the table-driven per-pair transition, fingerprints,
        #      and the shared merge on ≤B real candidates only.
        # Every O(F·K) stage that remains is a pure elementwise pass.
        sparse_boundary = not has_trivial_boundary(enc)

        import jax as _jax

        use_sparse = self._use_sparse()
        if use_sparse:
            _res_shape = _jax.eval_shape(
                enc.step_slot_vec,
                _jax.ShapeDtypeStruct((W,), jnp.uint32),
                _jax.ShapeDtypeStruct((), jnp.uint32),
            )
            sparse_has_trunc = isinstance(_res_shape, tuple)
            # The transposed pair step: COLUMN-major successor block
            # out — the shape fingerprint_u32v_t folds coalesced and
            # the [W, F] frontier's class-local DUS consumes without
            # a transpose. The INPUT seam is backend-adaptive and
            # lives in ONE place (encoding.pair_step_seam, PERF.md
            # §layout): TPU row-gathers off a per-wave seam
            # transpose; XLA:CPU column-gathers the resident buffer
            # directly (measured at paxos-4 peak-wave shapes: seam-T
            # + row gather 1.13s vs direct column gather 0.86s vs
            # the old row-major 1.35s step+fp).
            step_cols, make_pair_states = pair_step_seam(
                enc, cpu_backend
            )
        else:
            sparse_has_trunc = False

        def sparse_class_params(fc: int) -> dict:
            """Static per-frontier-class shapes of the sparse wave —
            ONE home shared by ``make_sparse_wave`` and the memory
            ledger's per-class staging rows (``_build_info``), so the
            plan the ``memory_plan`` event declares cannot drift from
            the classes the wave programs compile."""
            F_f = f_ladder[fc]
            EV = self._pair_width()
            NPg = F_f * EV
            B_p = min(B_user, NPg)
            compaction = NPg > B_p
            want_tiles = -(-NPg // self.tile_rows)
            if F_f == F:
                want_tiles = max(want_tiles, self.tiles)
            if compaction:
                # Packed append needs ONE TILE of headroom past the
                # pair budget; with few tiles that headroom is
                # NPg/NT ≈ half the grid (ABD ordered 2c/3s: Ba blew
                # to 2.8x the budget and the 128x-padded [Ba, 1] step
                # temps OOMed the chip). Keep the headroom ≤ B_p/4.
                want_tiles = max(
                    want_tiles, -(-(4 * NPg) // max(B_p, 1))
                )
            NT = _divisor_at_least(F_f, want_tiles) if compaction else 1
            T = F_f // NT
            Ba = (B_p + T * EV) if compaction else NPg
            # Memory-lean mode: when the [Ba, W] successor tensor would
            # blow the flat budget (paxos check 4: 28M pairs × 19 lanes
            # ≈ 2GB at merge-time peak), fingerprint pairs in chunks
            # without materializing successors, and RECOMPUTE the ≤F
            # winning rows' successors at fetch time. Extra cost: one
            # step_slot pass over the winners; saving: the whole [Ba,W]
            # tensor is never alive.
            # Chunk-mode gate and chunk count use the PADDED row cost
            # (~512 B/row on TPU for any [N, W<=32] buffer — PERF.md):
            # gating on unpadded W*4 bytes let an ABD-ordered probe
            # build ~86GB of padded step temps at Ba=8.4M (round 5).
            row_pad = -(-W // 128) * 512
            chunked = compaction and (
                Ba * row_pad > self.flat_budget_bytes
            )
            NC = Bc = 0
            if chunked:
                NC = -(-(Ba * row_pad) // self.flat_budget_bytes)
                Bc = -(-Ba // NC)
                Ba = NC * Bc  # pad so chunks tile it exactly
            # Fetch mode (PERF.md §gathers): keep the [Ba, W+3] packed
            # candidate payload (successor lanes + key limbs + parent
            # row) alive through the merge when its PADDED residency —
            # 512 B per 128-lane group on TPU, so ceil(EP/128)*512
            # B/row (a hardcoded 512 undercounted packed payloads
            # wider than 128 lanes by the full multiple, ADVICE r5) —
            # fits the flat budget, so the winners' fetch is ONE
            # multi-lane gather + one frontier-meta gather. Otherwise
            # fetch recomputes winners' successors from a packed
            # 4-lane (key_lo, key_hi, pair, slot) meta gather (the
            # chunked path never materializes [Ba, W] at all).
            pay_row_pad = -(-payload_width(W, track_paths) // 128) * 512
            pay_fetch = (not chunked) and (
                Ba * pay_row_pad <= self.flat_budget_bytes
            )
            return dict(
                F_f=F_f, EV=EV, NPg=NPg, B_p=B_p,
                compaction=compaction, NT=NT, T=T, Ba=Ba,
                row_pad=row_pad, chunked=chunked, NC=NC, Bc=Bc,
                pay_fetch=pay_fetch,
            )

        def make_sparse_wave(fc: int, v_class):
            p = sparse_class_params(fc)
            F_f, EV, B_p = p["F_f"], p["EV"], p["B_p"]
            NT, T, Ba = p["NT"], p["T"], p["Ba"]
            chunked, pay_fetch = p["chunked"], p["pay_fetch"]
            NC, Bc = p["NC"], p["Bc"]

            def wave(c):
                if target_depth is None:
                    expand = jnp.bool_(True)
                else:
                    expand = c["depth"] < target_depth
                frontier_t = c["frontier"][:, :F_f]
                fval_f = c["fval"][:F_f]
                ebits_f = c["ebits"][:F_f]
                cond, eb, f_lo, f_hi = frontier_props_t(
                    enc, props, evt_idx, frontier_t, fval_f, ebits_f,
                    sym_spec=sym,
                )

                pidx, live, pslot, cnt, n_pairs, pair_ovf, tile_max = (
                    sparse_pair_candidates(
                        # the FULL resident buffer + explicit class
                        # width: a strided column-prefix slice as a
                        # loop operand would materialize a per-wave
                        # copy (see the n_rows note on the pipeline)
                        enc, c["frontier"], fval_f, expand,
                        EV=EV, B_p=B_p, NT=NT, T=T,
                        mask_budget_cells=self.mask_budget_cells,
                        Ba=Ba, n_rows=F_f, ample_words=ample_words,
                    )
                )
                # Pair-state gather seam: the shared backend policy
                # (encoding.pair_step_seam) — pair rows are < F_f by
                # construction, so the CPU column gather can read the
                # full carry buffer (aliases for free).
                pair_states = make_pair_states(c["frontier"],
                                               frontier_t)
                c_overflow = c["c_overflow"] | pair_ovf
                e_overflow = c["e_overflow"]
                needs_scan = sparse_boundary or sparse_has_trunc

                def eval_pairs(pidx_b, live_b, slot_b):
                    """fingerprint keys + transposed successors +
                    validity (+ scan stats) for a block of compacted
                    pairs. ``step_cols`` returns ``(succ_t[W, n],
                    trunc|None, hard|None)``: trunc marks pairs pruned
                    by an internal encoding bound (compiled envelope
                    counts) — excluded from candidates and, when
                    in-boundary, raised as e_overflow (the dense
                    truncation contract); hard marks unrepresentable
                    successors (un-harvested history transitions) —
                    excluded and raised REGARDLESS of boundary, since
                    the garbage successor can't faithfully evaluate
                    it. The fingerprint fold runs lane-major over the
                    [W, n] block (fingerprint_u32v_t, the 1.65x
                    coalesced fold)."""
                    prow_b = pidx_b // jnp.uint32(EV)
                    succ_t, ptr_b, hard_b = step_cols(
                        pair_states(prow_b), slot_b
                    )
                    eov = jnp.bool_(False)
                    if hard_b is not None:
                        eov = jnp.any(live_b & hard_b)
                        live_b = live_b & ~hard_b
                    if sparse_boundary:
                        inb = within_boundary_cols(enc, succ_t)
                        ok = live_b & inb
                    else:
                        ok = live_b
                    if ptr_b is not None:
                        eov = eov | jnp.any(ok & ptr_b)
                        ok = ok & ~ptr_b
                    hits = None
                    if sym is not None:
                        # Canonical fingerprint, concrete successor
                        # block: succ_t flows untouched to the fetch /
                        # frontier write — the canonical block exists
                        # only to feed the fold (and the hits lane).
                        canon_t = canonicalize_t(sym, succ_t, jnp)
                        lo, hi = fingerprint_u32v_t(canon_t, jnp)
                        if trace_log:
                            hits = jnp.sum(
                                ok & (canon_t != succ_t).any(axis=0),
                                dtype=jnp.uint32,
                            )
                    else:
                        lo, hi = fingerprint_u32v_t(succ_t, jnp)
                    lo, hi = clamp_keys(lo, hi)
                    lo = jnp.where(ok, lo, jnp.uint32(_SENT))
                    hi = jnp.where(ok, hi, jnp.uint32(_SENT))
                    return lo, hi, ok, prow_b, eov, succ_t, hits

                if chunked:
                    # Chunked fingerprint pass: the [Ba, W] successor
                    # tensor is never materialized.
                    def fchunk(ti, acc):
                        cl, ch, nc, eov, rok, wvc = acc
                        off = ti * Bc
                        pidx_b = lax.dynamic_slice(pidx, (off,), (Bc,))
                        live_b = lax.dynamic_slice(live, (off,), (Bc,))
                        slot_b = lax.dynamic_slice(pslot, (off,), (Bc,))
                        lo, hi, ok, prow_b, ev, _succ, hits = eval_pairs(
                            pidx_b, live_b, slot_b
                        )
                        cl = lax.dynamic_update_slice(cl, lo, (off,))
                        ch = lax.dynamic_update_slice(ch, hi, (off,))
                        if hits is not None:
                            wvc = wvc + hits
                        if needs_scan:
                            nc = nc + jnp.sum(ok, dtype=jnp.uint32)
                            rok = rok.at[
                                jnp.where(ok, prow_b, jnp.uint32(F_f))
                            ].max(jnp.uint32(1), mode="drop")
                        return cl, ch, nc, eov | ev, rok, wvc

                    (ck_lo, ck_hi, nc_acc, eov_acc, row_ok,
                     wv_canon) = lax.fori_loop(
                        0,
                        NC,
                        fchunk,
                        (
                            jnp.full(Ba, _SENT, jnp.uint32),
                            jnp.full(Ba, _SENT, jnp.uint32),
                            jnp.uint32(0),
                            jnp.bool_(False),
                            jnp.zeros(F_f if needs_scan else 1,
                                      jnp.uint32),
                            jnp.uint32(0),
                        ),
                    )
                    e_overflow = e_overflow | eov_acc
                    if needs_scan:
                        has_succ = row_ok != 0
                        n_cand = nc_acc
                    else:
                        has_succ = cnt > 0
                        n_cand = n_pairs
                else:
                    (ck_lo, ck_hi, pair_ok, prow, eov,
                     succ_t, wv_canon) = eval_pairs(pidx, live, pslot)
                    if pay_fetch and not cpu_backend:
                        # Without this barrier XLA fuses the pair-step
                        # producer (frontier/params/sendtab gathers +
                        # the whole transition ALU) separately into
                        # BOTH consumers — the fingerprint path and
                        # the payload concat — running every pair-stage
                        # gather twice per wave (seen in the round-5
                        # device trace as duplicate [Ba, *] gather
                        # fusions). Materialize once; the extra
                        # [W, Ba] write is bandwidth-cheap.
                        ck_lo, ck_hi, succ_t, prow = (
                            lax.optimization_barrier(
                                (ck_lo, ck_hi, succ_t, prow)
                            )
                        )
                    e_overflow = e_overflow | eov
                    if needs_scan:
                        # Terminal = no surviving successor at all:
                        # scatter-max surviving pairs onto their rows.
                        row_ok = jnp.zeros(F_f, jnp.uint32).at[
                            jnp.where(pair_ok, prow, jnp.uint32(F_f))
                        ].max(jnp.uint32(1), mode="drop")
                        has_succ = row_ok != 0
                        n_cand = jnp.sum(pair_ok, dtype=jnp.uint32)
                    else:
                        has_succ = cnt > 0
                        n_cand = n_pairs
                terminal = fval_f & ~has_succ & expand
                evt_cex = terminal & (eb != 0)
                exd = dict(
                    cond=cond, ebits=eb, evt_cex=evt_cex,
                    f_lo=f_lo, f_hi=f_hi,
                )
                disc_found, disc_lo, disc_hi = discovery_update(
                    props, exd, fval_f,
                    c["disc_found"], c["disc_lo"], c["disc_hi"],
                )

                if pay_fetch and not cpu_backend:
                    # Packed candidate payload kept alive through the
                    # merge: winners' states, key limbs, and parent
                    # meta (ebits + parent fp, pre-gathered per pair as
                    # one [Ba, 1-3] gather) ride ONE multi-lane fetch
                    # gather — on TPU a gather costs ~12ns/row
                    # regardless of lane count (PERF.md §gathers).
                    # Payload staging is the ONE place the successor
                    # block transposes back to rows: gathers win
                    # row-major (the sanctioned seam copy; payload
                    # gathers measured equal either way).
                    fr_meta = jnp.stack(
                        [eb] + ([f_lo, f_hi] if track_paths else []),
                        axis=1,
                    )
                    pm = fr_meta[prow]
                    pay = payload_pack(
                        jnp, succ_t.T, ck_lo, ck_hi, pm[:, 0],
                        pm[:, 1] if track_paths else None,
                        pm[:, 2] if track_paths else None,
                    )

                    def fetch(nf_row):
                        st, p_lo, p_hi, eb_w, k_lo_w, k_hi_w = (
                            payload_unpack(pay[nf_row], W, track_paths)
                        )
                        # seam transpose of the small winner block
                        return st.T, p_lo, p_hi, eb_w, k_lo_w, k_hi_w
                elif pay_fetch:
                    # XLA:CPU workaround (round 5): gathering a
                    # CONCATENATED [Ba, W+k] payload in this sparse
                    # program livelocks the XLA:CPU thunk runtime
                    # inside the chunk while-loop (one Eigen thread
                    # spins forever; bisected to exactly this op
                    # arrangement — the same packed fetch is fine in
                    # the dense wave, and fine on TPU). Same math,
                    # separate gathers: the successor tensor is still
                    # reused (no transition recompute), and the
                    # column gather off [W, Ba] already returns the
                    # [W, n] block the frontier write wants (measured
                    # on CPU: cheaper than materializing a [Ba, W]
                    # row view first — the fetch touches only the
                    # winner columns).
                    def fetch(nf_row):
                        par_row = pidx[nf_row] // jnp.uint32(EV)
                        return (
                            succ_t[:, nf_row],
                            f_lo[par_row] if track_paths else None,
                            f_hi[par_row] if track_paths else None,
                            eb[par_row],
                            ck_lo[nf_row],
                            ck_hi[nf_row],
                        )
                else:
                    # Recompute mode (chunked or over-budget payload):
                    # winners' successors are recomputed from their
                    # (row, slot) pairs — exact by the
                    # SparseEncodedModel purity contract. Index-feeding
                    # gathers stay 1-D (the XLA:CPU hazard above), and
                    # step_cols hands back the [W, n] block directly —
                    # this path is transpose-free end to end.
                    def fetch(nf_row):
                        pidx_w = pidx[nf_row]
                        par_row = pidx_w // jnp.uint32(EV)
                        succ_w_t, _, _ = step_cols(
                            pair_states(par_row), pslot[nf_row]
                        )
                        return (
                            succ_w_t,
                            f_lo[par_row] if track_paths else None,
                            f_hi[par_row] if track_paths else None,
                            eb[par_row],
                            ck_lo[nf_row],
                            ck_hi[nf_row],
                        )

                return merge_stage(
                    c, v_class, Ba, ck_lo, ck_hi, fetch,
                    n_cand, disc_found, disc_lo, disc_hi,
                    c_overflow, e_overflow,
                    jnp.maximum(c["max_tile_cand"], tile_max),
                    jnp.maximum(c["max_rowen"], jnp.max(cnt)),
                    wv_pairs=n_pairs,
                    wv_canon=wv_canon,
                )

            return wave

        def body(c):
            n_f = c["n_frontier"]
            # tiered runs dispatch the v-ladder on the HOT count (the
            # rows actually resident) — the whole point of the tier:
            # the on-device membership/merge scale with hot, not with
            # the cumulative unique count
            u = c["n_hot"] if tier_mode else c["new"]
            f_class = jnp.int32(0)
            for F_i in f_ladder[:-1]:
                f_class = f_class + (n_f > jnp.uint32(F_i)).astype(jnp.int32)
            v_class = jnp.int32(0)
            for V_i in v_ladder[:-1]:
                v_class = v_class + (u > jnp.uint32(V_i)).astype(jnp.int32)
            mk = make_sparse_wave if use_sparse else make_wave
            c2 = lax.switch(
                f_class,
                [mk(fc, v_class) for fc in range(len(f_ladder))],
                c,
            )
            if trace_log and tier_mode:
                # the wave-log row can't be written yet — new/unique
                # settle at the NEXT dispatch's commit; stash the
                # wave-time lanes for it
                c2 = dict(
                    c2,
                    pstash=jnp.stack(
                        [
                            n_f,
                            c2["wv_pairs"],
                            c2["gen_lo"] - c["gen_lo"],
                            c["depth"].astype(jnp.uint32),
                            f_class.astype(jnp.uint32),
                            v_class.astype(jnp.uint32),
                            c2["wv_canon"],
                            jnp.uint32(0),
                        ]
                    ),
                )
                return c2
            if trace_log:
                # One wave-log row (telemetry.WAVE_LOG_FIELDS): the
                # pre/post carry delta gives candidates (gen counter)
                # and new states; wv_pairs carries the enabled
                # popcount out of the merge. Row index = wchunk (the
                # within-chunk wave number, always < waves_per_sync
                # while the loop runs).
                row = jnp.stack(
                    [
                        n_f,
                        c2["wv_pairs"],
                        c2["gen_lo"] - c["gen_lo"],
                        c2["new"] - c["new"],
                        c2["new"],
                        c["depth"].astype(jnp.uint32),
                        f_class.astype(jnp.uint32),
                        v_class.astype(jnp.uint32),
                        # optional lane 8 (WAVE_LOG_OPT_FIELDS):
                        # candidates whose canonical form differed
                        # from the raw successor this wave.
                        c2["wv_canon"],
                    ]
                )
                c2 = dict(
                    c2,
                    wlog=lax.dynamic_update_slice(
                        c2["wlog"], row[None, :],
                        (c["wchunk"], jnp.int32(0)),
                    ),
                )
            return c2

        # Tiered dispatches run exactly ONE wave: the commit phase
        # needs the host's membership verdict between waves.
        wps_eff = 1 if tier_mode else waves_per_sync

        def cond(c):
            return ~c["done"] & (c["wchunk"] < wps_eff)

        if not tier_mode:
            # Tooling hook: the un-jitted wave body, re-traceable on a
            # captured carry (stateright_tpu/wavewall.py times/lowers
            # ONE wave in isolation — the chunk program hides per-wave
            # structure inside the while_loop) or on eval_shape
            # abstract carries (stateright_tpu/analysis/lint.py walks
            # the traced switch branches for the no-branch-pad-concat
            # rule and the carry-copy-bytes estimator, never
            # allocating buffers). The tiered build must not clobber
            # the untiered hook the lint/profiler fixtures read.
            self._wave_body = body

        # Memory ledger (stateright_tpu/memplan.py): per-ladder-class
        # staging rows, recorded AT BUILD so the memory_plan event is
        # a function of the (f, v) class — the shapes come from the
        # SAME class_params/sparse_class_params the wave programs
        # compile from. CHUNKED memory-lean classes additionally land
        # an ``engine_mode`` record (emitted as a telemetry event at
        # run time): until round 12 that flip was observable only as
        # a docstring behavior.
        from ..memplan import buffer_entry, plan_total

        EPw = payload_width(W, track_paths)
        _classes = []
        _modes = []
        for fc in range(len(f_ladder)):
            if use_sparse:
                p = sparse_class_params(fc)
                staging = [
                    buffer_entry("enabled_bits",
                                 (p["F_f"], mask_words(K)), "uint32"),
                    buffer_entry("pair_index", (3, p["Ba"]), "uint32"),
                    buffer_entry("cand_keys", (2, p["Ba"]), "uint32"),
                ]
                if sym is not None:
                    # the canonicalization pass materializes the
                    # canonical successor block beside the concrete
                    # one (per chunk when memory-lean)
                    staging.append(buffer_entry(
                        "canonical_t",
                        (W, p["Bc"] if p["chunked"] else p["Ba"]),
                        "uint32",
                    ))
                if p["chunked"]:
                    mode = "chunked"
                    staging.append(
                        buffer_entry("succ_chunk", (W, p["Bc"]),
                                     "uint32")
                    )
                    _modes.append(dict(
                        engine=type(self).__name__, mode="chunked",
                        f_class=fc, buffer_rows=p["Ba"],
                        chunks=p["NC"], chunk_rows=p["Bc"],
                        row_pad_bytes=p["row_pad"],
                        flat_budget_bytes=self.flat_budget_bytes,
                    ))
                elif p["pay_fetch"]:
                    mode = "pay_fetch"
                    staging.append(
                        buffer_entry("cand_payload", (p["Ba"], EPw),
                                     "uint32")
                    )
                else:
                    mode = "recompute"
                    staging.append(
                        buffer_entry("succ_t", (W, p["Ba"]), "uint32")
                    )
                _classes.append(dict(
                    f_class=fc, mode=mode, frontier_rows=p["F_f"],
                    pair_width=p["EV"], budget_rows=p["B_p"],
                    tiles=p["NT"], buffer_rows=p["Ba"],
                    staging=staging, staging_bytes=plan_total(staging),
                ))
            else:
                (F_f, FK, NT_d, _T, _Bt, B_eff, Ba_d, B_class,
                 _compaction, full_flat) = class_params(fc)
                if full_flat:
                    mode = "full_flat"
                    rows = Ba_d
                    staging = [
                        buffer_entry("succ_flat", (FK, W), "uint32"),
                        buffer_entry("cand_keys", (3, rows), "uint32"),
                    ]
                    if sym is not None:
                        staging.append(buffer_entry(
                            "canonical_rows", (FK, W), "uint32"
                        ))
                else:
                    mode = "tile_payload"
                    rows = B_eff
                    staging = [
                        buffer_entry("cand_keys", (2, rows), "uint32"),
                        buffer_entry("cand_payload", (rows, EPw),
                                     "uint32"),
                    ]
                _classes.append(dict(
                    f_class=fc, mode=mode, frontier_rows=F_f,
                    budget_rows=B_class, tiles=NT_d, buffer_rows=rows,
                    staging=staging, staging_bytes=plan_total(staging),
                ))
        from ..memplan import v_class_entries

        _NFmax = min(F, max(c["buffer_rows"] for c in _classes))
        if not tier_mode:
            self._build_info = dict(
                classes=_classes,
                v_classes=v_class_entries(v_ladder, _NFmax),
                engine_modes=_modes,
            )

        def pack_stats(c):
            scalars = jnp.stack(
                [
                    c["done"].astype(jnp.uint32),
                    c["overflow"].astype(jnp.uint32),
                    c["f_overflow"].astype(jnp.uint32),
                    c["depth"].astype(jnp.uint32),
                    c["waves"],
                    jnp.sum(c["fval"]).astype(jnp.uint32),
                    c["gen_lo"],
                    c["gen_hi"],
                    c["new"],
                    c["c_overflow"].astype(jnp.uint32),
                    c["e_overflow"].astype(jnp.uint32),
                ]
            )
            parts = [
                scalars,
                c["disc_found"].astype(jnp.uint32),
                c["disc_lo"],
                c["disc_hi"],
                jnp.stack([c["max_cand"], c["max_tile_cand"],
                           c["max_rowen"]]),
            ]
            if trace_log:
                # The wave log rides the SAME packed readback — no
                # extra sync (waves_per_sync × WL uint32 ≈ 2 KB).
                parts.append(c["wlog"].reshape(-1))
            return jnp.concatenate(parts)

        def chunk(carry):
            c = dict(carry, wchunk=jnp.int32(0))
            c = lax.while_loop(cond, body, c)
            return c, pack_stats(c)

        if not tier_mode:
            return jax.jit(seed), jax.jit(chunk, donate_argnums=0)

        # -- the tiered chunk program (stateright_tpu/tier.py) -----------

        def tier_commit(c, keep):
            """Commit the PREVIOUS wave's survivors under the host's
            cold-membership ``keep`` mask: order-preserving compaction
            of the staged rows (one F-scale stable sort — kept rows
            stay in key order, the order every consumer shares), the
            hot-tier merge under the v-ladder switch sized by the HOT
            count, the parent-log append, and the counter/termination
            updates the untiered merge_stage would have made. A carry
            with ``pend_valid=False`` (the handoff dispatch) passes
            through untouched."""
            pv = c["pend_valid"]
            rowsF = jnp.arange(F, dtype=jnp.uint32)
            keepm = keep & (rowsF < c["pend_n"])
            conf = jnp.sum(keepm).astype(jnp.uint32)
            drop = jnp.where(keepm, jnp.uint32(0), jnp.uint32(1))
            _, perm = lax.sort((drop, rowsF), num_keys=1)
            confv = rowsF < conf
            front_c = jnp.where(
                confv[None, :], c["frontier"][:, perm], jnp.uint32(0)
            )
            eb_c = jnp.where(confv, c["ebits"][perm], jnp.uint32(0))
            k_lo = jnp.where(
                confv, c["pend_keys"][0][perm], jnp.uint32(_SENT)
            )
            k_hi = jnp.where(
                confv, c["pend_keys"][1][perm], jnp.uint32(_SENT)
            )

            v_class = jnp.int32(0)
            for V_i in v_ladder[:-1]:
                v_class = v_class + (
                    c["n_hot"] > jnp.uint32(V_i)
                ).astype(jnp.int32)

            def app(vc):
                V_v = v_ladder[vc]

                def br(_):
                    m_lo, m_hi = merge_sorted(
                        c["vkeys"][0, :V_v], c["vkeys"][1, :V_v],
                        k_lo, k_hi, impl=self.merge_impl,
                    )
                    return lax.dynamic_update_slice(
                        c["vkeys"],
                        jnp.stack([m_lo, m_hi]),
                        (jnp.uint32(0), jnp.uint32(0)),
                    )

                return br

            vkeys_m = lax.switch(
                v_class, [app(vc) for vc in range(len(v_ladder))], 0
            )

            def sel(a, b):
                return jnp.where(pv, a, b)

            confp = jnp.where(pv, conf, jnp.uint32(0))
            new2 = c["new"] + confp
            n_hot2 = c["n_hot"] + confp
            all_disc = (
                jnp.all(c["disc_found"]) if n_props
                else jnp.bool_(False)
            )
            if target_states is None:
                target_hit = jnp.bool_(False)
            else:
                target_hit = new2 >= jnp.uint32(target_states)
            overflow = c["overflow"] | (
                pv & (n_hot2 > jnp.uint32(C))
            )
            cont = (
                pv & (conf > 0) & ~all_disc & ~target_hit
                & ~overflow & ~c["f_overflow"] & ~c["c_overflow"]
                & ~c["e_overflow"]
            )
            out = dict(
                c,
                vkeys=sel(vkeys_m, c["vkeys"]),
                frontier=sel(front_c, c["frontier"]),
                ebits=sel(eb_c, c["ebits"]),
                fval=sel(confv & cont, c["fval"]),
                n_frontier=sel(conf, c["n_frontier"]),
                n_hot=n_hot2,
                new=new2,
                depth=jnp.where(cont, c["depth"] + 1, c["depth"]),
                waves=c["waves"] + jnp.where(
                    pv, jnp.uint32(1), jnp.uint32(0)
                ),
                overflow=overflow,
                done=sel(~cont, c["done"]),
                pend_valid=jnp.bool_(False),
                pend_n=jnp.uint32(0),
            )
            if track_paths:
                p_lo = jnp.where(
                    confv, c["pend_par"][0][perm], jnp.uint32(0)
                )
                p_hi = jnp.where(
                    confv, c["pend_par"][1][perm], jnp.uint32(0)
                )
                rows4 = jnp.stack([
                    p_lo,
                    p_hi,
                    jnp.where(confv, k_lo, jnp.uint32(0)),
                    jnp.where(confv, k_hi, jnp.uint32(0)),
                ])
                plog2 = lax.dynamic_update_slice(
                    c["plog"], rows4, (jnp.uint32(0), c["pl_n"])
                )
                out["plog"] = sel(plog2, c["plog"])
                out["pl_n"] = c["pl_n"] + confp
            if trace_log:
                st = c["pstash"]
                row = jnp.stack([
                    st[0], st[1], st[2], conf, new2,
                    st[3], st[4], st[5], st[6],
                ])
                out["wlog"] = lax.dynamic_update_slice(
                    c["wlog"], row[None, :],
                    (jnp.int32(0), jnp.int32(0)),
                )
            return out

        def tier_chunk(carry, keep):
            c = dict(carry, wchunk=jnp.int32(0))
            c = tier_commit(c, keep)
            c = lax.while_loop(cond, body, c)
            return c, pack_stats(c)

        return jax.jit(tier_chunk, donate_argnums=0)

    def _vec_fp(self, row) -> int:
        """Host fingerprints use the same all-ones clamp as the device
        keys (clamp_keys): the parent log stores clamped child
        fingerprints, so host and device keys must be defined
        identically or a state whose true 64-bit fingerprint is
        all-ones (p ~ 2^-64, same class as the NonZero convention)
        would fail path reconstruction."""
        fp = super()._vec_fp(row)
        if fp == 0xFFFFFFFFFFFFFFFF:
            fp = (0xFFFFFFFE << 32) | 0xFFFFFFFF
        return fp

    def _consume_extra_stats(self, extra: np.ndarray) -> None:
        if extra.size >= 3:
            self.metrics["max_wave_candidates"] = int(extra[0])
            self.metrics["max_tile_candidates"] = int(extra[1])
            #: exact per-row enabled-slot peak (sparse mode), the
            #: auto-budget pair_width sizer — computed from the mask
            #: counts, so it is correct even on an overflow run.
            self.metrics["max_row_enabled"] = int(extra[2])

    # -- reconstruction ----------------------------------------------------

    def _capture_final(self, carry) -> None:
        self._final_tables = (
            carry["vkeys"],
            carry["plog"],
            carry["pl_n"],
            carry["new"],
        )

    def _build_generated(self):
        """Materialize child→parent from the append-only device log
        (the lazy download; roots are simply absent from the log).

        The log carries BOTH key pairs (round 10): parent limbs in
        lanes 0-1, child limbs in lanes 2-3. Round 9 derived the
        children positionally from ``vkeys`` (the visited append WAS
        the insertion order); the incrementally-sorted visited array
        re-orders its rows every wave, so the log is the insertion-
        order record again."""
        if self.generated is None:
            tier = self._tier_generated_map()
            if tier is not None:
                # tiered runs drain the log host-side per dispatch
                # (stateright_tpu/tier.py): the accumulation IS the
                # insertion-order record
                self.generated = tier
                return self.generated
            _vkeys, plog, pl_n, _new = (
                np.asarray(a) for a in self._final_tables
            )
            n = int(pl_n)
            child = (
                plog[3, :n].astype(np.uint64) << np.uint64(32)
            ) | plog[2, :n].astype(np.uint64)
            parent = (
                plog[1, :n].astype(np.uint64) << np.uint64(32)
            ) | plog[0, :n].astype(np.uint64)
            self.generated = {
                int(c): (int(p) if p else None)
                for c, p in zip(child.tolist(), parent.tolist())
            }
        return self.generated
