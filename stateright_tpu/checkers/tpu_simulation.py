"""Device-side simulation: N parallel random walks under ``vmap``.

The accelerator re-design of the reference's simulation checker
(src/checker/simulation.rs): where one host thread walks one trace at
a time from init to terminal/cycle/boundary, the device advances N
walks per step in lockstep — ``vmap`` over the encoded ``step_vec``,
a per-walk uniform choice among the valid successors, and property
bitmaps folded into per-property discovery flags, all inside a jitted
``lax.fori_loop`` so the host reads back one packed stats vector per
run.

Semantics relative to the reference:

* Walks that reach a terminal state (no valid successor) check
  surviving EventuallyBits (an eventually-counterexample,
  checker.rs:559-566) and then RESTART from an init state with a fresh
  ebits mask — the device analog of simulation.rs:180-364's
  trace-per-iteration loop.
* Per-trace cycle detection (simulation.rs:207, 250-261 keeps a host
  HashSet per trace) is replaced by the ``max_steps`` walk bound:
  cycles simply burn steps until the bound restarts the walk. Cycles
  are therefore treated as non-terminal for eventually properties —
  the same documented false-negative class as the reference's
  revisit behavior (bfs.rs:285-303).
* ``unique_state_count`` is approximate and equals ``state_count``,
  exactly as in the reference (simulation.rs:380-384).

Randomness is counter-based (splitmix64 over (seed, step) folded with
the walk index), so runs are reproducible for a fixed seed and walk
count, mirroring the derived per-trace seeds of simulation.rs:114-167.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..checker import Checker, CheckerBuilder
from ..encoding import EncodedModel
from ..model import Expectation
from ..ops.fingerprint import fingerprint_u32v
from ..path import Path
from ..report import ReportData, Reporter
from .tpu import TpuBfsChecker, _fp_int, step_with_trunc


class TpuSimulationChecker(TpuBfsChecker):
    """``CheckerBuilder.spawn_tpu_simulation()`` — N vmapped random
    walks. With ``track_paths=True`` (default) the device keeps a
    per-walk fingerprint trace ring; on each property's FIRST discovery
    the hitting walk's trace is frozen into a per-property buffer, and
    ``discoveries()`` replays it through the host model into a real
    :class:`Path` — the device counterpart of the trace the reference's
    simulation checker keeps per iteration
    (src/checker/simulation.rs:180-364)."""

    def __init__(
        self,
        builder: CheckerBuilder,
        encoded: Optional[EncodedModel] = None,
        n_walks: int = 1024,
        max_steps: int = 64,
        rounds: int = 4,
        seed: int = 0,
        track_paths: bool = True,
    ):
        super().__init__(
            builder,
            encoded=encoded,
            capacity=1,
            frontier_capacity=1,
            track_paths=track_paths,
        )
        self.n_walks = n_walks
        self.max_steps = max_steps
        self.rounds = rounds
        self.seed = seed
        #: per-property frozen traces: name -> [fp, ...] (uint64)
        self._disc_traces: dict[str, list[int]] = {}

    def _cache_extras(self) -> tuple:
        return ("tpu-sim", self.n_walks, self.max_steps, self.rounds,
                self.seed, self.track_paths)

    def discoveries(self):
        self._ensure_run()
        if not self.track_paths and self._discovered_fps:
            raise RuntimeError(
                "paths unavailable with track_paths=False; use "
                "discovered_property_names()/discovery_fingerprints(), "
                "or re-run with track_paths=True for replayable traces"
            )
        out = {}
        for name, fps in self._disc_traces.items():
            out[name] = self._replay_trace(fps)
        return out

    def _replay_trace(self, fps: list[int]) -> Path:
        """Replay a fingerprint trace through the HOST model (the same
        differential the wave engine's path reconstruction performs —
        every step must re-encode to the recorded fingerprint)."""
        import numpy as np

        model = self.model
        enc = self.encoded
        state = None
        for init_state in model.init_states():
            vec = np.asarray(enc.encode(init_state), np.uint32)
            if self._vec_fp(vec) == fps[0]:
                state = init_state
                break
        if state is None:
            raise RuntimeError(
                f"no init state encodes to fingerprint {fps[0]:#x}; "
                "encode()/init_vecs() disagree"
            )
        steps = []
        for next_fp in fps[1:]:
            found = False
            for action in model.actions(state):
                next_state = model.next_state(state, action)
                if next_state is None:
                    continue
                vec = np.asarray(enc.encode(next_state), np.uint32)
                if self._vec_fp(vec) == next_fp:
                    steps.append((state, action))
                    state = next_state
                    found = True
                    break
            if not found:
                raise RuntimeError(
                    f"no host successor encodes to {next_fp:#x}: the "
                    "device walk disagrees with the host model"
                )
        steps.append((state, None))
        return Path(steps)

    # -- device program ----------------------------------------------------

    def _build_programs(self, n0: int):
        import jax
        import jax.numpy as jnp
        from jax import lax

        enc = self.encoded
        props = list(self.model.properties())
        n_props = len(props)
        evt_idx = [
            i for i, p in enumerate(props)
            if p.expectation == Expectation.EVENTUALLY
        ]
        if evt_idx and max(evt_idx) >= 32:
            raise ValueError(
                "the TPU engine supports eventually properties only at "
                "property indices < 32; reorder properties() so eventually "
                f"properties come first (got index {max(evt_idx)})"
            )
        K, W = enc.max_actions, enc.width
        N = self.n_walks
        max_steps = self.max_steps
        rounds = self.rounds
        seed = self.seed
        ebits_init = self._eventually_bits_init()
        track_paths = self.track_paths
        LT = max_steps + 1  # trace ring length (depth starts at 1)

        def rand_bits(step, salt):
            """Counter-based per-walk uniform bits: splitmix over
            (seed, step, salt) mixed with the walk index."""
            base = jnp.uint32(seed) ^ (
                step.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
            ) ^ jnp.uint32((salt * 0x85EBCA6B) & 0xFFFFFFFF)
            rows = jnp.stack(
                [
                    jnp.broadcast_to(base, (N,)),
                    jnp.arange(N, dtype=jnp.uint32),
                ],
                axis=1,
            )
            lo, _ = fingerprint_u32v(rows, jnp)
            return lo

        def seed_fn(init_rows):
            # Each walk starts at a (cyclically assigned) init state.
            idx = jnp.arange(N, dtype=jnp.uint32) % jnp.uint32(n0)
            walks = init_rows[idx]
            ebits = jnp.full(N, jnp.uint32(ebits_init))
            LTt = LT if track_paths else 1
            return dict(
                walks=walks,
                ebits=ebits,
                walk_depth=jnp.ones(N, jnp.uint32),
                steps=jnp.uint32(0),
                states=jnp.uint32(N),  # init states count as visited
                depth=jnp.uint32(1),
                disc_found=jnp.zeros(n_props, dtype=bool),
                disc_lo=jnp.zeros(n_props, dtype=jnp.uint32),
                disc_hi=jnp.zeros(n_props, dtype=jnp.uint32),
                e_ovf=jnp.bool_(False),
                # Per-walk fingerprint trace ring + per-property frozen
                # traces (the hitting walk's prefix at first discovery).
                trace_lo=jnp.zeros((N, LTt), jnp.uint32),
                trace_hi=jnp.zeros((N, LTt), jnp.uint32),
                dt_lo=jnp.zeros((n_props, LTt), jnp.uint32),
                dt_hi=jnp.zeros((n_props, LTt), jnp.uint32),
                dt_len=jnp.zeros(n_props, jnp.uint32),
                init=init_rows,
            )

        def eval_block(walks, ebits, c):
            """Property bitmap + discovery folding over a walk block;
            returns (succs, valid, terminal, ebits', disc/trace
            updates)."""
            f_lo, f_hi = fingerprint_u32v(walks, jnp)
            if track_paths:
                # Record each walk's CURRENT state at its depth slot —
                # idempotent, so the end-of-round re-evaluation is safe.
                pos = jnp.minimum(c["walk_depth"] - 1, jnp.uint32(LT - 1))
                rows_i = jnp.arange(N)
                trace_lo = c["trace_lo"].at[rows_i, pos].set(f_lo)
                trace_hi = c["trace_hi"].at[rows_i, pos].set(f_hi)
            else:
                trace_lo, trace_hi = c["trace_lo"], c["trace_hi"]
            if n_props:
                cond = jax.vmap(enc.property_conditions_vec)(walks)
            else:
                cond = jnp.zeros((N, 0), dtype=bool)
            for i in evt_idx:
                ebits = jnp.where(
                    cond[:, i], ebits & ~jnp.uint32(1 << i), ebits
                )

            succs, valid, trunc = step_with_trunc(enc, walks, jnp)
            trunc_any = jnp.any(trunc)
            bound = jax.vmap(
                lambda row: jax.vmap(enc.within_boundary_vec)(row)
            )(succs)
            valid = valid & bound
            n_valid = jnp.sum(valid, axis=1)
            terminal = n_valid == 0

            disc_found = c["disc_found"]
            disc_lo, disc_hi = c["disc_lo"], c["disc_hi"]
            dt_lo, dt_hi, dt_len = c["dt_lo"], c["dt_hi"], c["dt_len"]
            for i, p in enumerate(props):
                if p.expectation == Expectation.ALWAYS:
                    mask = ~cond[:, i]
                elif p.expectation == Expectation.SOMETIMES:
                    mask = cond[:, i]
                else:
                    mask = terminal & (
                        (ebits & jnp.uint32(1 << i)) != 0
                    )
                hit = jnp.any(mask)
                row = jnp.argmax(mask)
                fresh = hit & ~disc_found[i]
                disc_found = disc_found.at[i].set(disc_found[i] | hit)
                disc_lo = disc_lo.at[i].set(
                    jnp.where(fresh, f_lo[row], disc_lo[i])
                )
                disc_hi = disc_hi.at[i].set(
                    jnp.where(fresh, f_hi[row], disc_hi[i])
                )
                if track_paths:
                    # Freeze the hitting walk's trace prefix before its
                    # ring slots are recycled by a restart.
                    dt_lo = dt_lo.at[i].set(
                        jnp.where(fresh, trace_lo[row], dt_lo[i])
                    )
                    dt_hi = dt_hi.at[i].set(
                        jnp.where(fresh, trace_hi[row], dt_hi[i])
                    )
                    dt_len = dt_len.at[i].set(
                        jnp.where(
                            fresh, c["walk_depth"][row], dt_len[i]
                        )
                    )
            return (succs, valid, n_valid, terminal, ebits,
                    disc_found, disc_lo, disc_hi, trunc_any,
                    trace_lo, trace_hi, dt_lo, dt_hi, dt_len)

        def step_once(step, c, salt):
            walks = c["walks"]
            (
                succs, valid, n_valid, terminal, ebits,
                disc_found, disc_lo, disc_hi, trunc_any,
                trace_lo, trace_hi, dt_lo, dt_hi, dt_len,
            ) = eval_block(walks, c["ebits"], c)

            # Uniform choice among the valid successors of each walk.
            r = rand_bits(step, salt)
            pick = r % jnp.maximum(n_valid, 1).astype(jnp.uint32)
            csum = jnp.cumsum(valid, axis=1)
            choice = jnp.argmax(csum > pick[:, None], axis=1)
            nxt = jnp.take_along_axis(
                succs, choice[:, None, None], axis=1
            )[:, 0]

            # Terminal walks restart from a (rotating) init state with
            # fresh ebits (simulation.rs trace-per-iteration).
            restart_idx = (
                jnp.arange(N, dtype=jnp.uint32)
                + step.astype(jnp.uint32)
            ) % jnp.uint32(n0)
            restart = c["init"][restart_idx]
            nxt = jnp.where(terminal[:, None], restart, nxt)
            ebits = jnp.where(
                terminal, jnp.uint32(ebits_init), ebits
            )
            # Per-walk depth: +1 per transition, reset on restart; the
            # reported max_depth is the deepest TRACE, not the loop
            # step counter.
            walk_depth = jnp.where(
                terminal, jnp.uint32(1), c["walk_depth"] + 1
            )
            return dict(
                walks=nxt,
                ebits=ebits,
                walk_depth=walk_depth,
                steps=c["steps"] + 1,
                states=c["states"] + jnp.uint32(N),
                depth=jnp.maximum(c["depth"], jnp.max(walk_depth)),
                disc_found=disc_found,
                disc_lo=disc_lo,
                disc_hi=disc_hi,
                e_ovf=c["e_ovf"] | trunc_any,
                trace_lo=trace_lo,
                trace_hi=trace_hi,
                dt_lo=dt_lo,
                dt_hi=dt_hi,
                dt_len=dt_len,
                init=c["init"],
            )

        def run(init_rows):
            c = seed_fn(init_rows)
            for salt in range(rounds):
                # Each round is one bounded walk segment; walks restart
                # between rounds for trace diversity.
                c = lax.fori_loop(
                    0,
                    max_steps,
                    lambda s, cc: step_once(s, cc, salt),
                    c,
                )
                # The round's FINAL states were generated and counted
                # inside the loop but not yet property-checked —
                # evaluate them before restarting the walks.
                (_, _, _, _, _, disc_found, disc_lo, disc_hi,
                 trunc_any, trace_lo, trace_hi, dt_lo, dt_hi,
                 dt_len) = (
                    eval_block(c["walks"], c["ebits"], c)
                )
                idx = (
                    jnp.arange(N, dtype=jnp.uint32)
                    + jnp.uint32(salt)
                ) % jnp.uint32(n0)
                c = dict(
                    c,
                    walks=init_rows[idx],
                    ebits=jnp.full(N, jnp.uint32(ebits_init)),
                    walk_depth=jnp.ones(N, jnp.uint32),
                    disc_found=disc_found,
                    disc_lo=disc_lo,
                    disc_hi=disc_hi,
                    e_ovf=c["e_ovf"] | trunc_any,
                    trace_lo=trace_lo,
                    trace_hi=trace_hi,
                    dt_lo=dt_lo,
                    dt_hi=dt_hi,
                    dt_len=dt_len,
                )
            stats = jnp.concatenate(
                [
                    jnp.stack(
                        [
                            c["states"],
                            c["depth"],
                            c["e_ovf"].astype(jnp.uint32),
                        ]
                    ),
                    c["disc_found"].astype(jnp.uint32),
                    c["disc_lo"],
                    c["disc_hi"],
                    c["dt_len"],
                    c["dt_lo"].reshape(-1),
                    c["dt_hi"].reshape(-1),
                ]
            )
            return stats

        return jax.jit(run), None

    # -- host orchestration ------------------------------------------------

    def _run(self, reporter: Optional[Reporter] = None) -> None:
        import jax.numpy as jnp

        enc = self.encoded
        props = list(self.model.properties())
        n_props = len(props)
        init = np.asarray(enc.init_vecs(), dtype=np.uint32).reshape(
            -1, enc.width
        )
        n0 = init.shape[0]
        if n0 == 0:
            return
        if self._programs is None:
            self._programs = self._lookup_programs(n0)
        run_fn, _ = self._programs
        stats = np.asarray(run_fn(jnp.asarray(init)))
        self._total_states = int(stats[0])
        self._unique_states = int(stats[0])  # approximate, as reference
        self._max_depth = int(stats[1])
        if bool(stats[2]):
            raise RuntimeError(
                "encoding-bound overflow: a walk hit a successor pruned "
                "by an internal encoding bound (e.g. a compiled envelope "
                "count reached 128); walk coverage would be silently "
                "truncated"
            )
        disc_found = stats[3 : 3 + n_props]
        disc_lo = stats[3 + n_props : 3 + 2 * n_props]
        disc_hi = stats[3 + 2 * n_props : 3 + 3 * n_props]
        off = 3 + 3 * n_props
        dt_len = stats[off : off + n_props]
        LT = self.max_steps + 1 if self.track_paths else 1
        dt_lo = stats[off + n_props : off + n_props + n_props * LT]
        dt_hi = stats[off + n_props + n_props * LT :]
        for i, prop in enumerate(props):
            if disc_found[i]:
                if prop.name not in self._discovered_fps:
                    from .. import telemetry

                    telemetry.emit(
                        "verdict", property=prop.name,
                        expectation=prop.expectation.name.lower(),
                        kind="discovery", wave=None,
                        depth=self._max_depth,
                    )
                self._discovered_fps[prop.name] = _fp_int(
                    disc_lo[i], disc_hi[i]
                )
                if self.track_paths:
                    ln = int(dt_len[i])
                    fps = [
                        _fp_int(
                            dt_lo[i * LT + j], dt_hi[i * LT + j]
                        )
                        for j in range(ln)
                    ]
                    self._disc_traces[prop.name] = fps
        if reporter is not None:
            reporter.report_checking(
                ReportData(
                    total_states=self._total_states,
                    unique_states=self._unique_states,
                    max_depth=self._max_depth,
                    duration_sec=self.duration_sec(),
                    done=True,
                )
            )
