"""Vectorized 64-bit state fingerprinting over uint32 lanes.

The device counterpart of the reference's stable fixed-key hasher
(src/lib.rs:329-375): encoded states are fixed-width ``uint32``
vectors; their digest is a splitmix64-style fold over the lanes with
hard-coded keys, built from the limb arithmetic in
:mod:`stateright_tpu.ops.u64` so jax.numpy (device) and numpy (host)
produce bit-identical results. Zero is reserved as the empty-slot
marker in the visited table, so a zero digest maps to 1 (the
``NonZeroU64`` convention, src/lib.rs:329-337).
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np

from .u64 import U64, u64_add, u64_const, u64_mul_const, u64_shr, u64_xor

_SEED = 0x51A7E12D_0BADC0DE
_GOLDEN = 0x9E3779B97F4A7C15
_MIX1 = 0xBF58476D1CE4E5B9
_MIX2 = 0x94D049BB133111EB


def splitmix64(z: U64, xp=np) -> U64:
    """The splitmix64 finalizer (Steele et al.), elementwise."""
    z = u64_xor(z, u64_shr(z, 30))
    z = u64_mul_const(z, _MIX1, xp)
    z = u64_xor(z, u64_shr(z, 27))
    z = u64_mul_const(z, _MIX2, xp)
    z = u64_xor(z, u64_shr(z, 31))
    return z


def _fold_lanes(w: int, lane, zero, xp) -> Tuple[Any, Any]:
    """THE fingerprint fold — one body for every lane layout.

    ``lane(i)`` returns lane ``i`` of the batch (the only thing the
    row-major and lane-major entry points differ in); the seed, the
    per-lane GOLDEN offsets, the splitmix64 chain, and the NonZeroU64
    zero-reservation live here exactly once, so a hash change cannot
    silently fork the host-side digests (drain, seeds) from the
    device-side ones (the transposed engines).
    """
    h = U64(zero + xp.uint32(_SEED & 0xFFFFFFFF), zero + xp.uint32(_SEED >> 32))
    for i in range(w):
        lane_i = u64_add(
            U64(lane(i), zero),
            u64_const(_GOLDEN * (i + 1) & 0xFFFFFFFFFFFFFFFF, xp),
        )
        h = splitmix64(u64_xor(h, lane_i), xp)
    # Reserve 0 as "empty" (NonZeroU64 convention).
    both_zero = (h.lo == 0) & (h.hi == 0)
    lo = xp.where(both_zero, xp.uint32(1), h.lo)
    return lo, h.hi


def fingerprint_u32v(vec: Any, xp=np) -> Tuple[Any, Any]:
    """Digest uint32 state vectors along the last axis.

    ``vec``: uint32[..., W] → ``(lo, hi)``: uint32[...] each, never
    both zero. The fold is sequential over the W lanes (W is small and
    static; XLA unrolls it) and vectorized over every leading axis.
    """
    vec = xp.asarray(vec, dtype=xp.uint32)
    zero = xp.zeros(vec.shape[:-1], dtype=xp.uint32)
    return _fold_lanes(
        vec.shape[-1], lambda i: vec[..., i], zero, xp
    )


def fingerprint_u32v_t(vec_t: Any, xp=np) -> Tuple[Any, Any]:
    """Digest TRANSPOSED (lane-major) state blocks along axis 0.

    ``vec_t``: uint32[W, ...] → ``(lo, hi)``: uint32[...] each —
    bit-identical to ``fingerprint_u32v(vec_t.T)`` (same
    :func:`_fold_lanes` body, only the lane accessor differs). This
    is the fold the engines run over the column-major ``[W, N]``
    resident layout (PERF.md §layout): lane ``i`` is the contiguous
    row ``vec_t[i]``, so the per-lane splitmix64 pass streams
    coalesced instead of striding through T(8,128)-tiled rows (the
    measured 1.65x fold, PERF.md §tile-padding).
    """
    vec_t = xp.asarray(vec_t, dtype=xp.uint32)
    zero = xp.zeros(vec_t.shape[1:], dtype=xp.uint32)
    return _fold_lanes(
        vec_t.shape[0], lambda i: vec_t[i], zero, xp
    )


def fingerprint_u32v_int(vec: Any) -> Any:
    """Host helper: digests as Python-friendly uint64 numpy array."""
    lo, hi = fingerprint_u32v(np.asarray(vec, dtype=np.uint32), np)
    return (np.asarray(hi, dtype=np.uint64) << np.uint64(32)) | np.asarray(
        lo, dtype=np.uint64
    )
