"""Vectorized 64-bit state fingerprinting over uint32 lanes.

The device counterpart of the reference's stable fixed-key hasher
(src/lib.rs:329-375): encoded states are fixed-width ``uint32``
vectors; their digest is a splitmix64-style fold over the lanes with
hard-coded keys, built from the limb arithmetic in
:mod:`stateright_tpu.ops.u64` so jax.numpy (device) and numpy (host)
produce bit-identical results. Zero is reserved as the empty-slot
marker in the visited table, so a zero digest maps to 1 (the
``NonZeroU64`` convention, src/lib.rs:329-337).
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np

from .u64 import U64, u64_add, u64_const, u64_mul_const, u64_shr, u64_xor

_SEED = 0x51A7E12D_0BADC0DE
_GOLDEN = 0x9E3779B97F4A7C15
_MIX1 = 0xBF58476D1CE4E5B9
_MIX2 = 0x94D049BB133111EB


def splitmix64(z: U64, xp=np) -> U64:
    """The splitmix64 finalizer (Steele et al.), elementwise."""
    z = u64_xor(z, u64_shr(z, 30))
    z = u64_mul_const(z, _MIX1, xp)
    z = u64_xor(z, u64_shr(z, 27))
    z = u64_mul_const(z, _MIX2, xp)
    z = u64_xor(z, u64_shr(z, 31))
    return z


def fingerprint_u32v(vec: Any, xp=np) -> Tuple[Any, Any]:
    """Digest uint32 state vectors along the last axis.

    ``vec``: uint32[..., W] → ``(lo, hi)``: uint32[...] each, never
    both zero. The fold is sequential over the W lanes (W is small and
    static; XLA unrolls it) and vectorized over every leading axis.
    """
    vec = xp.asarray(vec, dtype=xp.uint32)
    w = vec.shape[-1]
    zero = xp.zeros(vec.shape[:-1], dtype=xp.uint32)
    h = U64(zero + xp.uint32(_SEED & 0xFFFFFFFF), zero + xp.uint32(_SEED >> 32))
    for i in range(w):
        lane = u64_add(
            U64(vec[..., i], zero),
            u64_const(_GOLDEN * (i + 1) & 0xFFFFFFFFFFFFFFFF, xp),
        )
        h = splitmix64(u64_xor(h, lane), xp)
    # Reserve 0 as "empty" (NonZeroU64 convention).
    both_zero = (h.lo == 0) & (h.hi == 0)
    lo = xp.where(both_zero, xp.uint32(1), h.lo)
    return lo, h.hi


def fingerprint_u32v_int(vec: Any) -> Any:
    """Host helper: digests as Python-friendly uint64 numpy array."""
    lo, hi = fingerprint_u32v(np.asarray(vec, dtype=np.uint32), np)
    return (np.asarray(hi, dtype=np.uint64) << np.uint64(32)) | np.asarray(
        lo, dtype=np.uint64
    )
