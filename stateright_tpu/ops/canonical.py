"""Device-side symmetry canonicalization: vectorized RewritePlan.

The host symmetry reduction (stateright_tpu/symmetry.py, reference
representative.rs) maps each state to a canonical member of its
permutation orbit before visited-set insertion — 2pc with 5 RMs drops
from 8,832 to 665 states. This module is the device analog: an
encoding whose interchangeable participants occupy UNIFORMLY STRIDED
bit-fields declares a :class:`DeviceRewriteSpec`, and the engines run
:func:`canonicalize_t` over every candidate block BEFORE the
fingerprint fold, so the visited key is the canonical fingerprint
while the frontier keeps the concrete states (the same
visited-through-representatives / search-through-originals split the
host DFS implements, dfs.rs:300-311).

The kernel is deliberately GATHER-FREE (the codegen lint rules,
analysis/rules.py, gate it like the enabled-bits pass):

* the stable sort permutation is computed as comparison-count RANKS —
  ``rank[m] = sum_j (key[j] < key[m])`` over keys made distinct by an
  embedded member-index tiebreak, which reproduces EXACTLY the host
  ``RewritePlan.from_values_to_sort`` stable sort (Python ``sorted``
  is stable; ties resolve by original index there too);
* the permutation is APPLIED as comparison-based one-hot select-sums
  — ``out[p] = sum_m (rank[m] == p) * val[m]`` — R^2 lane-ALU ops for
  R members, no ``jnp.take``, no dense [B, K] masks.

Everything here is module-generic over the array namespace (``xp`` =
``jax.numpy`` on device, ``numpy`` on host), the same pattern as
ops/fingerprint.py — host path reconstruction canonicalizes encoded
rows with BIT-IDENTICAL math before fingerprinting, so the parent-log
keys the device wrote and the keys the host replay computes can never
drift.

:func:`validate_spec` checks the spec's STRUCTURAL invariants (field
bounds, key-bit budget). The SEMANTIC soundness of a declared spec —
that the rewrite set really is a group action, that properties and
the fingerprint are invariant under it — is the reduction soundness
analyzer's job (stateright_tpu/analysis/soundness.py): the engines
run it at spawn and refuse uncertifiable specs, so ``validate_spec``
passing is necessary but deliberately NOT sufficient to arm the
reduction.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np


@dataclass(frozen=True)
class MemberField:
    """One per-member bit-field: member ``m``'s value occupies bits
    ``[shift + m*stride, shift + m*stride + width)`` of ``lane``.

    Fields with ``sort_key=True`` form the stable-sort key, major to
    minor in declaration order. To mirror a host representative that
    sorts on a SUBSET of the per-member state (e.g. 2pc sorts on
    rm_state only), mark exactly that subset as keys — the comparison
    ranks embed the member index as the final tiebreak, so the device
    permutation equals the host's stable sort."""

    lane: int
    shift: int
    stride: int
    width: int
    sort_key: bool = False


@dataclass(frozen=True)
class DeviceRewriteSpec:
    """The declared symmetry of an encoding's interchangeable limb
    group: ``n_members`` participants whose per-member state lives in
    the strided :class:`MemberField` s. Canonicalization permutes ALL
    fields by the stable sort over the key fields — the vectorized
    counterpart of ``RewritePlan.from_values_to_sort`` + ``reindex``
    (+ the prepared-message rewrite, which for a strided bitmask IS a
    reindex of the mask bits)."""

    n_members: int
    fields: Tuple[MemberField, ...] = field(default_factory=tuple)

    def __post_init__(self):
        validate_spec(self)


def _idx_bits(n_members: int) -> int:
    bits = 1
    while (1 << bits) < n_members:
        bits += 1
    return bits


def validate_spec(spec: DeviceRewriteSpec,
                  width: Optional[int] = None) -> None:
    """Loud structural validation — a malformed spec must refuse at
    declaration, not mis-canonicalize (silent under-exploration is the
    checker's worst failure mode)."""
    R = spec.n_members
    if R < 2:
        raise ValueError(
            f"DeviceRewriteSpec needs >= 2 interchangeable members "
            f"(got {R}); a singleton group has nothing to permute"
        )
    if not spec.fields:
        raise ValueError("DeviceRewriteSpec declares no member fields")
    key_bits = 0
    for f in spec.fields:
        if f.width < 1 or f.stride < f.width:
            raise ValueError(
                f"MemberField(lane={f.lane}): width {f.width} must be "
                f">= 1 and <= stride {f.stride} (members must not "
                "overlap)"
            )
        top = f.shift + (R - 1) * f.stride + f.width
        if top > 32:
            raise ValueError(
                f"MemberField(lane={f.lane}, shift={f.shift}): member "
                f"{R - 1}'s bits end at {top} > 32 — the strided group "
                "must fit one uint32 lane"
            )
        if width is not None and not (0 <= f.lane < width):
            raise ValueError(
                f"MemberField lane {f.lane} outside encoding width "
                f"{width}"
            )
        if f.sort_key:
            key_bits += f.width
    if key_bits == 0:
        raise ValueError(
            "DeviceRewriteSpec has no sort_key fields — the canonical "
            "order would be undefined"
        )
    if key_bits + _idx_bits(R) > 32:
        raise ValueError(
            f"sort key ({key_bits} bits) + member-index tiebreak "
            f"({_idx_bits(R)} bits) exceeds 32 — the packed rank key "
            "must fit one uint32"
        )


def _field_mask(f: MemberField) -> int:
    return (1 << f.width) - 1


def _group_clear_mask(spec: DeviceRewriteSpec, lane: int) -> int:
    """Host-constant: every member bit of every field on ``lane``."""
    m = 0
    for f in spec.fields:
        if f.lane != lane:
            continue
        for i in range(spec.n_members):
            m |= _field_mask(f) << (f.shift + i * f.stride)
    return m & 0xFFFFFFFF


def _canonicalize_lanes(spec: DeviceRewriteSpec, lanes: list, xp):
    """The kernel body over a list of uint32 lane arrays (any common
    batch shape). Returns the canonical lanes; untouched lanes pass
    through by reference."""
    R = spec.n_members
    u32 = lanes[0].dtype
    ib = _idx_bits(R)

    # Per-member field values, extracted once (shift-mask lane ALU).
    vals = []  # vals[fi][m]
    for f in spec.fields:
        fm = np.uint32(_field_mask(f))
        vals.append([
            (lanes[f.lane] >> np.uint32(f.shift + m * f.stride)) & fm
            for m in range(R)
        ])

    # Packed stable-sort keys: key fields major-to-minor, the member
    # index as the final tiebreak — distinct by construction, so the
    # comparison ranks ARE the host stable-sort permutation
    # (rank[m] = new position of member m; RewritePlan.inverse).
    keys = []
    for m in range(R):
        k = None
        for fi, f in enumerate(spec.fields):
            if not f.sort_key:
                continue
            v = vals[fi][m].astype(u32)
            k = v if k is None else (
                (k << np.uint32(f.width)) | v
            )
        k = (k << np.uint32(ib)) | np.uint32(m)
        keys.append(k)
    ranks = [
        sum(
            (keys[j] < keys[m]).astype(u32)
            for j in range(R) if j != m
        )
        for m in range(R)
    ]
    # One-hot permutation grid, computed once and reused per field:
    # sel[p][m] is True where member m lands at output position p.
    sel = [[ranks[m] == np.uint32(p) for m in range(R)]
           for p in range(R)]

    out = list(lanes)
    touched = sorted({f.lane for f in spec.fields})
    for lane in touched:
        acc = out[lane] & np.uint32(
            ~_group_clear_mask(spec, lane) & 0xFFFFFFFF
        )
        for fi, f in enumerate(spec.fields):
            if f.lane != lane:
                continue
            for p in range(R):
                v = sum(
                    xp.where(sel[p][m], vals[fi][m], np.uint32(0))
                    for m in range(R)
                )
                acc = acc | (v << np.uint32(f.shift + p * f.stride))
        out[lane] = acc
    return out


def canonicalize_t(spec: DeviceRewriteSpec, states_t, xp):
    """``uint32[W, N] -> uint32[W, N]`` — canonicalize a TRANSPOSED
    (column-major, PERF.md §layout) state block: each column maps to
    its orbit representative. Lane reads are row slices of the
    resident block; all math is elementwise over ``[N]`` lane rows."""
    W = states_t.shape[0]
    lanes = [states_t[i] for i in range(W)]
    return xp.stack(_canonicalize_lanes(spec, lanes, xp))


def canonicalize_rows(spec: DeviceRewriteSpec, rows, xp):
    """Row-major variant: ``uint32[..., W] -> uint32[..., W]`` (used
    by the dense engine paths and the HOST replay — a single encoded
    ``uint32[W]`` row canonicalizes with the identical math, which is
    what keeps the parent-log keys and the host ``_vec_fp`` bit-equal)."""
    W = rows.shape[-1]
    lanes = [rows[..., i] for i in range(W)]
    return xp.stack(_canonicalize_lanes(spec, lanes, xp), axis=-1)


def canonical_hits(raw_t, canon_t, xp):
    """``uint32`` count of columns whose canonical form differs from
    the raw successor — the per-wave ``canonical_hits`` telemetry lane
    (how much symmetry is actually folding this wave)."""
    changed = (raw_t != canon_t).any(axis=0)
    return changed.sum().astype(raw_t.dtype)


def canonicalize_vec(spec: DeviceRewriteSpec, vec, xp):
    """One state: ``uint32[W] -> uint32[W]`` (the lint registry's
    row-contract view; vmapping this equals :func:`canonicalize_t`
    up to layout)."""
    return canonicalize_rows(spec, vec, xp)
