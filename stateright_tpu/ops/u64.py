"""64-bit arithmetic as ``uint32`` limb pairs.

TPUs have no native 64-bit integers (and jax defaults to x64-disabled
everywhere), so the device fingerprint path represents a ``u64`` as a
``(lo, hi)`` pair of ``uint32`` arrays and implements the mixing
arithmetic (add-with-carry, 32x32→64 multiply via 16-bit half-words)
directly. All functions are elementwise on uint32 *arrays* (any
shape) and dtype-polymorphic between numpy and jax.numpy — the
identical code runs on device and host, so host and device compute
bit-identical digests. That property is what makes host-side trace
reconstruction possible, mirroring how the reference relies on one
stable hasher everywhere (src/lib.rs:357-375).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import numpy as np

_MASK16 = np.uint32(0xFFFF)


class U64(NamedTuple):
    """A 64-bit value as two uint32 limbs (elementwise arrays)."""

    lo: Any
    hi: Any


def u64_const(value: int, xp=np) -> U64:
    return U64(
        xp.uint32(value & 0xFFFFFFFF), xp.uint32((value >> 32) & 0xFFFFFFFF)
    )


def u64_xor(a: U64, b: U64) -> U64:
    return U64(a.lo ^ b.lo, a.hi ^ b.hi)


def u64_add(a: U64, b: U64) -> U64:
    lo = a.lo + b.lo  # uint32 arrays wrap
    carry = (lo < a.lo).astype(np.uint32)
    return U64(lo, a.hi + b.hi + carry)


def u64_shr(a: U64, n: int) -> U64:
    """Logical right shift by a static amount 0 < n < 64."""
    if n >= 32:
        zero = a.hi ^ a.hi
        return U64(a.hi >> np.uint32(n - 32), zero)
    return U64(
        (a.lo >> np.uint32(n)) | (a.hi << np.uint32(32 - n)),
        a.hi >> np.uint32(n),
    )


def _mul32x32(a, b) -> Tuple[Any, Any]:
    """Full 64-bit product of two uint32 arrays, as (lo, hi) limbs."""
    a0 = a & _MASK16
    a1 = a >> np.uint32(16)
    b0 = b & _MASK16
    b1 = b >> np.uint32(16)
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = (p00 >> np.uint32(16)) + (p01 & _MASK16) + (p10 & _MASK16)
    lo = (p00 & _MASK16) | ((mid & _MASK16) << np.uint32(16))
    hi = p11 + (p01 >> np.uint32(16)) + (p10 >> np.uint32(16)) + (mid >> np.uint32(16))
    return lo, hi


def u64_mul(a: U64, b: U64) -> U64:
    """Low 64 bits of the product:
    ``a.lo*b.lo + ((a.lo*b.hi + a.hi*b.lo) << 32)``."""
    lo, hi = _mul32x32(a.lo, b.lo)
    hi = hi + a.lo * b.hi + a.hi * b.lo
    return U64(lo, hi)


def u64_mul_const(a: U64, value: int, xp=np) -> U64:
    return u64_mul(a, u64_const(value, xp))
