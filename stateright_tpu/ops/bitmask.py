"""Packed enabled-mask bitmaps: the mask lane of the sparse dispatch.

The sparse action-dispatch pipeline (checkers/tpu_sortmerge.py
``sparse_pair_candidates``) consumes the per-state enabled mask as
``ceil(K/32)`` uint32 words per row, GPUexplore-style (guards compiled
to bitwise ops over packed words, arXiv:1801.05857) — the peel loop
and the pair compaction never touch a dense ``[F, K]`` bool tensor.
This module is the single home of the word layout so the three
producers/consumers can't drift:

* encodings that only provide a dense ``bool[K]`` mask
  (``enabled_mask_vec``) are packed by the ENGINE with
  :func:`mask_to_words`;
* encodings that build the packed words directly from shift-mask field
  extracts (``enabled_bits_vec`` — the compiled actor codegen, PERF.md
  §ordered) hand the engine ``uint32[L]`` rows and skip the dense mask
  entirely; :func:`words_to_mask` recovers the bool view for the
  ``SparseEncodedModel`` contract (and its differential tests) without
  a gather;
* :func:`popcount_words` supplies the per-row enabled counts that size
  the pair buffers.

Word layout (everywhere): slot ``k`` lives in word ``k // 32`` at bit
``k % 32``; tail bits of the last word are zero. The builders are
pinned against brute-force references over randomized inputs
(tests/test_bitmask_props.py, including the ``k % 32 == 0``
full-tail-word edge), and the gather-free / lane-shape contract of
everything that CONSUMES them is pinned by the kernel lint
(stateright_tpu/analysis/, ``pytest -m lint``).

**Word-level guard builders (round 6).** A hand encoding's enabled
predicate factors as "host-constant slot class × small state-dependent
selector" (a paxos slot is enabled iff its envelope bit is present AND
its destination's guard holds; a 2pc slot iff its RM/TM condition
holds). The builders below assemble the packed words directly from
that factorization — :func:`slot_mask_host` precomputes the class
masks, :func:`or_class_words` ORs them under traced scalar conditions,
:func:`select_words_host` picks a mask row by a traced field value
(the word-level analog of :func:`bit_select`) — so the predicate costs
O(L × classes) uint32 lane ops per state instead of O(K) slot
evaluations, and no dense ``bool[K]`` row ever exists (PERF.md
§wave-wall: the [F, K] mask pass was the largest in-stage term at
paxos-4 shapes, 199M cells per wave for 686k real pairs).
"""

from __future__ import annotations


def mask_words(k: int) -> int:
    """Words per row for a K-slot mask."""
    return (int(k) + 31) // 32


def pack_bits_host(flags) -> tuple:
    """Host-side packing of a bool sequence into this module's word
    layout (bit ``i`` of word ``i // 32``), the format
    :func:`bit_select` reads. Always at least one word."""
    words = [0] * max(1, mask_words(len(flags)))
    for i, f in enumerate(flags):
        if f:
            words[i // 32] |= 1 << (i % 32)
    return tuple(words)


def mask_to_words(jnp, mask):
    """``bool[..., K] -> uint32[..., ceil(K/32)]`` — pack a dense
    enabled mask into bitmap words (pad, reshape, weighted sum; pure
    elementwise + reduce, no gather)."""
    k = mask.shape[-1]
    L = mask_words(k)
    pad = [(0, 0)] * (mask.ndim - 1) + [(0, L * 32 - k)]
    mp = jnp.pad(mask, pad)
    return jnp.sum(
        mp.reshape(mask.shape[:-1] + (L, 32)).astype(jnp.uint32)
        * (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)),
        axis=-1,
        dtype=jnp.uint32,
    )


def words_to_mask(jnp, words, k: int):
    """``uint32[..., L] -> bool[..., K]`` — unpack bitmap words to the
    dense mask. Broadcast shifts + one static slice, no gather (the
    codegen-shape tests trace through this)."""
    from jax import lax

    bits = (
        words[..., :, None] >> jnp.arange(32, dtype=jnp.uint32)
    ) & jnp.uint32(1)
    flat = bits.reshape(words.shape[:-1] + (words.shape[-1] * 32,))
    return lax.slice_in_dim(flat, 0, k, axis=-1) != 0


def popcount_words(jnp, words):
    """``uint32[..., L] -> uint32[...]`` — set bits per row (the
    per-row enabled-slot count)."""
    from jax import lax

    return jnp.sum(
        lax.population_count(words), axis=-1, dtype=jnp.uint32
    )


def slot_mask_host(k: int, slots) -> tuple:
    """Host constant: the packed-word mask with exactly the given slot
    indices set (a guard CLASS — the slots sharing one enabling
    condition). Always ``mask_words(k)`` words."""
    words = [0] * mask_words(k)
    for s in slots:
        if not 0 <= s < k:
            raise ValueError(f"slot {s} outside 0..{k - 1}")
        words[s // 32] |= 1 << (s % 32)
    return tuple(words)


def const_words(jnp, words):
    """Host word tuple -> device constant: ``uint32[L]``, except a
    single-word mask becomes a SCALAR so vmapped guard math stays
    ``[N]``-shaped (a ``[N, 1]`` elementwise op pays the full 128-lane
    tile-padding tax on TPU — the PERF.md §ordered artifact; the
    callers below reshape to ``[1]`` only at the very end)."""
    import numpy as np

    if len(words) == 1:
        return jnp.uint32(words[0])
    return jnp.asarray(np.array(words, dtype=np.uint32))


def or_class_words(jnp, classes, L: int):
    """OR of condition-gated host class masks: ``classes`` is a
    sequence of ``(cond, words)`` with ``cond`` a traced scalar bool
    and ``words`` either a host tuple (from :func:`slot_mask_host`) or
    an already-built ``uint32[L]`` array (e.g. a
    :func:`select_words_host` result). Pure where/or lane ops — a
    vmapped caller stays ``[N, L]``-shaped, no ``[N, K]`` bool, no
    gather. All-zero host masks are dropped for free."""
    acc = None
    for cond, words in classes:
        if isinstance(words, tuple):
            if not any(words):
                continue
            words = const_words(jnp, words)
        term = jnp.where(cond, words, jnp.uint32(0))
        acc = term if acc is None else acc | term
    if acc is None:
        return jnp.zeros(L, jnp.uint32)
    # Single-word masks compute as scalars (see const_words); restore
    # the [L] row contract with one broadcast at the end.
    if acc.ndim == 0:
        acc = acc[None]
    return acc


def select_words_host(jnp, rows, idx):
    """Pick row ``idx`` (traced uint32 scalar) from a HOST-CONSTANT
    table of packed-word rows (``rows[v]`` = word tuple for field
    value ``v``). A static where-chain over the rows — the word-level
    analog of :func:`bit_select`: ``len(rows)`` selects of ``[L]``
    vectors (scalars when L=1, per const_words — AND the result into
    the presence words or an or_class_words accumulator, which
    restores the row shape), no gather. Callers tabulate
    per-field-value guard masks whose domains are small enums (ballot
    codes, phases), not state spaces. Out-of-range ``idx`` returns
    ``rows[0]``."""
    idx = idx.astype(jnp.uint32)
    acc = const_words(jnp, rows[0])
    for v in range(1, len(rows)):
        acc = jnp.where(
            idx == jnp.uint32(v), const_words(jnp, rows[v]), acc
        )
    return acc


def bit_run_plan(k: int, sources):
    """Host planner for the compiled-codegen mask optimizer (round 20):
    coalesce single-bit presence extracts into word-level runs.

    ``sources`` is a sequence of ``(slot, lane, shift)`` triples — slots
    whose enabled-presence is ONE state bit (duplicating-network
    envelope bits, timer armed bits). Wherever consecutive slots read
    consecutive shifts of the same lane (the layout builder allocates
    1-bit fields in slot order, so maximal runs are the common case),
    the whole run collapses to a single ``(vec[lane] >> shift) & mask``
    instead of per-slot extracts. Runs never cross an OUTPUT word
    boundary (slot 32 starts a new word). Returns a list of
    ``(dst_word, dst_pos, lane, shift, nbits)`` covering every source
    exactly once; :func:`or_bit_runs` assembles them."""
    runs = []
    cur = None  # [dst_word, dst_pos, lane, shift, nbits]
    for slot, lane, shift in sources:
        if not 0 <= slot < k:
            raise ValueError(f"slot {slot} outside 0..{k - 1}")
        w, p = slot // 32, slot % 32
        if (
            cur is not None
            and w == cur[0]
            and p == cur[1] + cur[4]
            and lane == cur[2]
            and shift == cur[3] + cur[4]
        ):
            cur[4] += 1
            continue
        if cur is not None:
            runs.append(tuple(cur))
        cur = [w, p, lane, shift, 1]
    if cur is not None:
        runs.append(tuple(cur))
    return runs


def or_bit_runs(jnp, vec, runs, L: int):
    """Traced counterpart of :func:`bit_run_plan`: OR each run's
    ``(vec[lane] >> shift) & ((1 << nbits) - 1)`` into its destination
    word. Returns a length-``L`` python list of per-word uint32 scalar
    accumulators (``None`` where no run landed) so the caller can fold
    in per-slot leftovers before materializing the ``[L]`` row — pure
    shift-mask lane ops, no gather, no dense bool."""
    u32 = jnp.uint32
    acc = [None] * L
    for dst_word, dst_pos, lane, shift, nbits in runs:
        term = (vec[lane] >> u32(shift)) & u32((1 << nbits) - 1)
        if dst_pos:
            term = term << u32(dst_pos)
        acc[dst_word] = (
            term if acc[dst_word] is None else acc[dst_word] | term
        )
    return acc


def bit_select(jnp, words, idx):
    """Gather-free bit lookup in a HOST-CONSTANT packed bit table.

    ``words`` is a python sequence of uint32 ints (bit ``i`` of word
    ``i // 32`` holds entry ``i``); ``idx`` is a traced uint32 scalar.
    The word is picked by a static where-chain and the bit by a shift —
    shift-mask ops only, so a vmapped caller stays 1-D ``[N]``-shaped
    (no gather, no ``[N, 1]`` temps). Cost is ``len(words)`` selects:
    callers tabulate per-slot, per-actor-state bits whose domains are
    component closures (tens of entries), not state spaces.
    """
    idx = idx.astype(jnp.uint32)
    w = jnp.uint32(words[0] if words else 0)
    for wi in range(1, len(words)):
        w = jnp.where(
            (idx >> jnp.uint32(5)) == jnp.uint32(wi),
            jnp.uint32(words[wi]),
            w,
        )
    return (w >> (idx & jnp.uint32(31))) & jnp.uint32(1)
