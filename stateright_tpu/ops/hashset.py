"""Device-resident visited set: open addressing over uint32 limb pairs.

The TPU counterpart of the reference BFS's sharded concurrent
``DashMap`` visited set (bfs.rs:28-29): a fixed-capacity power-of-two
table of 64-bit fingerprints stored as two ``uint32`` arrays (0,0 =
empty — fingerprints are never zero), with batched insert-if-absent.

Batched insertion resolves conflicts — including DUPLICATE keys within
one batch — without atomics: each still-active key reads its slot; on
empty it *claims* via ``scatter-max`` of its row index into a claim
array, then re-reads to learn the winner; a claim loser re-reads
before moving (the winner may hold its own key), and occupied-by-other
keys advance their private triangular probe sequence. This is the
classic GPU model-checker table insert (cf. GPUexplore), expressed as
XLA scatter/gather. (:func:`sort_unique` remains available for callers
that want an explicit pre-dedup pass.)

NOTE: on TPU hardware, XLA lowers these scatters poorly (~50x slower
than sorts at equal row counts — see checkers/tpu_sortmerge.py, which
is the TPU-preferred dedup built on sorts instead).

Everything is functional: ``insert`` returns the new table arrays.
The probe loop is a static Python loop (PROBE_ROUNDS is small) so XLA
unrolls and fuses it.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

PROBE_ROUNDS = 24


class DeviceHashSet(NamedTuple):
    """Table state (a pytree — pass through jit freely)."""

    lo: Any  # uint32[capacity]
    hi: Any  # uint32[capacity]

    @staticmethod
    def empty(capacity: int, xp) -> "DeviceHashSet":
        if capacity & (capacity - 1):
            raise ValueError(f"capacity must be a power of two: {capacity}")
        return DeviceHashSet(
            xp.zeros(capacity, dtype=xp.uint32),
            xp.zeros(capacity, dtype=xp.uint32),
        )

    @property
    def capacity(self) -> int:
        return self.lo.shape[0]


def _slot_hash(key_lo, key_hi, mask, xp):
    # Cheap avalanche of the already-mixed fingerprint into a slot.
    x = key_lo ^ (key_hi * xp.uint32(0x9E3779B9))
    x = x ^ (x >> xp.uint32(16))
    return x & mask


def sort_unique(key_lo, key_hi, xp):
    """Sort keys by (hi, lo) and mark the first occurrence of each.

    Returns ``((sorted_lo, sorted_hi, order), unique_mask)``: the keys
    in sorted order, the permutation ``order`` that produced them
    (gather other per-key arrays with it), and ``unique_mask[i]`` True
    iff sorted position i is the first of its key. Invalid entries
    should be pre-set to the all-ones key so they sort last (and
    collapse into one dup run).
    """
    n = key_lo.shape[0]
    idx = xp.arange(n, dtype=xp.uint32)
    if xp.__name__.startswith("jax"):
        import jax

        sorted_hi, sorted_lo, order = jax.lax.sort(
            (key_hi, key_lo, idx), num_keys=2
        )
    else:
        perm = xp.lexsort((key_lo, key_hi))
        sorted_hi, sorted_lo, order = key_hi[perm], key_lo[perm], idx[perm]
    prev_same = xp.zeros(n, dtype=bool)
    if n > 1:
        same = (sorted_hi[1:] == sorted_hi[:-1]) & (
            sorted_lo[1:] == sorted_lo[:-1]
        )
        prev_same = xp.concatenate([xp.zeros(1, dtype=bool), same])
    return (sorted_lo, sorted_hi, order), ~prev_same


def insert(
    table: DeviceHashSet,
    key_lo: Any,
    key_hi: Any,
    active: Any,
    xp,
    rounds: int = PROBE_ROUNDS,
) -> Tuple[DeviceHashSet, Any, Any, Any]:
    """Insert keys where ``active``; return
    ``(new_table, is_new, overflow, slot)``.

    ``is_new[i]`` — key i was inserted (absent before); ``overflow[i]``
    — probing exhausted without a slot (caller must grow + retry);
    ``slot[i]`` — the table index key i landed at (inserted or already
    present; undefined for inactive/overflowed rows). Slots let callers
    keep side tables indexed by table position — the engine stores the
    parent fingerprint of each visited state this way, so the whole
    parent forest stays device-resident (bfs.rs:28-29 equivalent).

    The batch may contain DUPLICATE keys: every row keeps its own
    probe position along the deterministic triangular sequence for its
    key, and a row that loses a claim race re-reads its slot before
    moving on — so of N rows with one key, exactly one reports
    ``is_new`` (if absent) and the rest find the winner's entry. This
    is what lets the engines skip a whole sort-unique pass per wave.
    """
    if xp.__name__.startswith("jax"):
        return _insert_jax(table, key_lo, key_hi, active, rounds)
    n = key_lo.shape[0]
    mask = xp.uint32(table.capacity - 1)
    row_ids = xp.arange(n, dtype=xp.uint32)
    idx = _slot_hash(key_lo, key_hi, mask, xp)
    probe = xp.zeros(n, dtype=xp.uint32)
    lo, hi = table.lo, table.hi
    lo, hi = lo.copy(), hi.copy()  # keep numpy path functional too
    inserted = xp.zeros(n, dtype=bool)
    overflow = xp.zeros(n, dtype=bool)
    slot = xp.zeros(n, dtype=xp.uint32)
    pending = active
    # Each round with pending rows makes progress, so n + rounds bounds
    # the loop; a key overflows when its probe DEPTH exhausts `rounds`
    # (claim-loser re-read rounds don't eat the probe budget).
    for _ in range(rounds + n):
        if not pending.any():
            break
        slot_lo = lo[idx]
        slot_hi = hi[idx]
        is_empty = (slot_lo == 0) & (slot_hi == 0)
        is_match = (slot_lo == key_lo) & (slot_hi == key_hi)
        slot = xp.where(pending & is_match, idx, slot)
        pending = pending & ~is_match
        # Claim empty slots: scatter-max row ids, winners re-read.
        want = pending & is_empty
        claims = xp.zeros(table.capacity, dtype=xp.uint32)
        import numpy as np

        np.maximum.at(
            claims, idx, xp.where(want, row_ids + 1, xp.uint32(0))
        )
        won = want & (claims[idx] == row_ids + 1)
        lo[idx[won]] = key_lo[won]
        hi[idx[won]] = key_hi[won]
        inserted = inserted | won
        slot = xp.where(won, idx, slot)
        pending = pending & ~won
        # Advance only rows that saw a different key; claim losers
        # re-read (the winner may hold their own key). Each row steps
        # its own triangular sequence so a key's probe path never
        # depends on batch contention.
        advance = pending & ~is_empty & ~is_match
        probe = xp.where(advance, probe + 1, probe)
        idx = xp.where(advance, (idx + probe) & mask, idx)
        exhausted = pending & (probe >= rounds)
        overflow = overflow | exhausted
        pending = pending & ~exhausted
    return DeviceHashSet(lo, hi), inserted, overflow | pending, slot


def _match_vma(x, vma):
    """Promote ``x`` to vary over the manual axes in ``vma`` (no-op
    outside shard_map). Needed because this module's while_loop carries
    mix fresh constants (unvarying) with shard-local keys (varying) —
    the vma checker requires carry in/out types to agree."""
    import jax
    from jax import lax

    typeof = getattr(jax, "typeof", None)
    if typeof is None:  # older jax: no vma typing, nothing to promote
        return x
    cur = getattr(typeof(x), "vma", frozenset())
    need = tuple(sorted(set(vma) - set(cur)))
    return lax.pcast(x, need, to="varying") if need else x


def _inputs_vma(*arrays) -> frozenset:
    import jax

    typeof = getattr(jax, "typeof", None)
    vma: frozenset = frozenset()
    if typeof is None:  # older jax: no vma typing
        return vma
    for a in arrays:
        vma = vma | getattr(typeof(a), "vma", frozenset())
    return vma


def _insert_jax(
    table: DeviceHashSet, key_lo: Any, key_hi: Any, active: Any, rounds: int
) -> Tuple[DeviceHashSet, Any, Any, Any]:
    """Device insert: the probe rounds run in a ``lax.while_loop`` that
    exits as soon as no key is pending. At sane load factors (<50%)
    nearly every batch resolves within 2-4 rounds, so this costs a
    fraction of a fixed ``rounds``-times-unrolled loop; ``rounds`` is
    the safety bound whose exhaustion reports overflow."""
    import jax.numpy as jnp
    from jax import lax

    vma = _inputs_vma(table.lo, table.hi, key_lo, key_hi, active)
    n = key_lo.shape[0]
    cap = table.capacity
    mask = jnp.uint32(cap - 1)
    row_ids = jnp.arange(n, dtype=jnp.uint32)

    def cond(c):
        # Every round with pending rows makes progress (an insertion,
        # a match, or a probe advance), so n + rounds bounds the loop;
        # per-key overflow is governed by probe DEPTH below, not by
        # the iteration count — claim-loser re-read rounds don't eat
        # a key's probe budget.
        return (c["r"] < rounds + n) & jnp.any(c["pending"])

    def body(c):
        lo, hi, idx, pending = c["lo"], c["hi"], c["idx"], c["pending"]
        slot_lo = lo[idx]
        slot_hi = hi[idx]
        is_empty = (slot_lo == 0) & (slot_hi == 0)
        is_match = (slot_lo == key_lo) & (slot_hi == key_hi)
        newly_found = pending & is_match
        slot = jnp.where(newly_found, idx, c["slot"])
        pending = pending & ~is_match
        # Claim empty slots: scatter-max row ids, winners re-read.
        want = pending & is_empty
        claims = jnp.zeros(cap, dtype=jnp.uint32).at[idx].max(
            jnp.where(want, row_ids + 1, jnp.uint32(0))
        )
        won = want & (claims[idx] == row_ids + 1)
        # Only winners write; losers scatter out of range (dropped).
        # A plain at[idx].set with stale values for losers would race
        # the winner's write at duplicate indices.
        write_idx = jnp.where(won, idx, jnp.uint32(cap))
        lo = lo.at[write_idx].set(key_lo, mode="drop")
        hi = hi.at[write_idx].set(key_hi, mode="drop")
        # Advance only rows that saw a DIFFERENT key; claim losers
        # re-read their slot next round (the winner may hold their own
        # key — that's how duplicate keys within a batch resolve).
        # Per-row probe counters keep each key's triangular sequence
        # deterministic regardless of contention, so later inserts and
        # contains() retrace the same path.
        pending = pending & ~won
        advance = pending & ~is_empty & ~is_match
        probe = jnp.where(advance, c["probe"] + 1, c["probe"])
        # A key whose probe depth exhausts `rounds` overflows and
        # leaves the pending set (reported to the caller).
        exhausted = pending & (probe >= rounds)
        return dict(
            lo=lo,
            hi=hi,
            idx=jnp.where(advance, (idx + probe) & mask, idx),
            probe=probe,
            pending=pending & ~exhausted,
            overflow=c["overflow"] | exhausted,
            inserted=c["inserted"] | won,
            slot=jnp.where(won, idx, slot),
            r=c["r"] + 1,
        )

    init = dict(
        lo=table.lo,
        hi=table.hi,
        idx=_slot_hash(key_lo, key_hi, mask, jnp),
        probe=jnp.zeros(n, dtype=jnp.uint32),
        pending=active,
        overflow=jnp.zeros(n, dtype=bool),
        inserted=jnp.zeros(n, dtype=bool),
        slot=jnp.zeros(n, dtype=jnp.uint32),
        r=jnp.int32(0),
    )
    init = {
        k: (_match_vma(v, vma) if k != "r" else v) for k, v in init.items()
    }
    out = lax.while_loop(cond, body, init)
    return (
        DeviceHashSet(out["lo"], out["hi"]),
        out["inserted"],
        out["overflow"] | out["pending"],
        out["slot"],
    )


def contains(
    table: DeviceHashSet, key_lo: Any, key_hi: Any, xp,
    rounds: int = PROBE_ROUNDS,
) -> Any:
    """Membership probe (no mutation)."""
    mask = xp.uint32(table.capacity - 1)
    idx = _slot_hash(key_lo, key_hi, mask, xp)
    found = xp.zeros(key_lo.shape, dtype=bool)
    missing = xp.zeros(key_lo.shape, dtype=bool)
    done = xp.zeros(key_lo.shape, dtype=bool)
    for r in range(rounds):
        slot_lo = table.lo[idx]
        slot_hi = table.hi[idx]
        is_empty = (slot_lo == 0) & (slot_hi == 0)
        is_match = (slot_lo == key_lo) & (slot_hi == key_hi)
        found = found | (~done & is_match)
        missing = missing | (~done & is_empty)
        done = done | is_match | is_empty
        idx = (idx + xp.uint32(r + 1)) & mask
    return found
