"""Streaming sorted-set kernels: the visited-dedup merge family.

Round 10 (PERF.md §merge-kernel) makes the engines' visited set
INCREMENTALLY SORTED, which turns the per-wave dedup from a
from-scratch ``(V + B)``-row stable 3-lane ``lax.sort`` — the
irreducible b·V term the round-5..9 work left standing (~3-20ms at
C=2²¹ on chip) — into two O(V + B) streaming passes over sorted runs:

* :func:`member_sorted` — for each of B sorted query keys, is it
  present in the sorted visited prefix (the dedup membership test);
* :func:`merge_sorted` — merge the ≤F sorted winner keys into the
  sorted visited prefix (the visited append).

Keys are 2-limb SoA ``uint32`` pairs ordered lexicographically by
``(hi, lo)`` with the all-ones pair as the trailing padding sentinel
(the engines' ``clamp_keys`` convention keeps real fingerprints off
it). Both inputs must be sorted ascending; ties order A-first (the
"visited wins" rule the old stable concat-sort implemented). A may
contain duplicates and sentinel tails; semantics are exact multiset
membership, so callers mask sentinel queries themselves (the engines
already gate on ``real``).

Each op ships two implementations, selected by the engines'
``merge_impl`` knob (auto: Pallas on TPU, XLA fallback elsewhere):

* ``impl="pallas"`` / ``"pallas_interpret"`` — a hand-written Pallas
  kernel: the merged output is partitioned into ``block``-row tiles by
  a Merge Path diagonal search (:func:`merge_path_starts`, computed in
  plain XLA — G+1 binary searches, negligible), and each grid step
  loads one bounded window of each input and produces its tile with a
  rank-based block merge (broadcast compare + one-hot reduce — all
  VPU-shaped work, no sort, no data-dependent control flow). Grid
  iteration order is the sequential TPU/interpreter order; the member
  kernel's overlapping window writes rely on it (last writer owns the
  tile's true query range). ``pallas_interpret`` runs the SAME kernel
  through the Pallas interpreter, which is what lets a CPU-only CI
  pin the kernel's semantics in tier-1 (tests/test_merge.py).
  The windows staged per grid step are ``block``-bounded; the backing
  refs are whole-array (fine under the interpreter and at the ≤C_pad
  VMEM-resident sizes the ladder classes produce today — chip-scale
  HBM staging via ``pltpu.ANY`` + double-buffered DMA is the
  BENCH_r06 follow-up, same as every chip-gated verdict).

* ``impl="xla"`` — a pure-XLA O(B log V + M) fallback for CPU and
  old-JAX paths: membership is a vectorized 2-limb binary search
  (log₂ V unrolled gather steps — fast on CPU where the sequential
  gathers are cache-friendly, catastrophic on TPU per the round-5
  primitive microbenchmarks — PERF.md "Primitive costs", re-runnable
  via ``tools/profile_stages.py --micro`` — which is exactly why
  the Pallas path exists); the merge computes winner destinations by
  binary search, scatters the ≤F winner flags, and assembles the
  merged array with one cumsum + two gathers — no sort anywhere.

Neither implementation contains an O(V)-row ``lax.sort``; the
codegen-shape audit (tests/test_merge.py::test_no_visited_scale_sort)
pins that for the whole steady-state wave body.
"""

from __future__ import annotations

from functools import partial

_SENT = 0xFFFFFFFF

#: merged rows per Pallas grid step. 512 keeps the block-merge's
#: [block, block] compare/one-hot temporaries at 1 MB (uint32) — VPU
#: lane-aligned and far under VMEM — while amortizing the per-step
#: window loads.
DEFAULT_BLOCK = 512

#: the merge_impl vocabulary the engines accept (None = auto).
IMPLS = ("xla", "pallas", "pallas_interpret")


def pallas_available() -> bool:
    try:  # gated: old-JAX paths fall back to the XLA impl
        from jax.experimental import pallas as _  # noqa: F401
    except Exception:
        return False
    return True


def default_impl() -> str:
    """Auto policy: the Pallas kernel where it wins (TPU), the XLA
    fallback everywhere else (CPU binary search beats interpreting
    the kernel by orders of magnitude)."""
    import jax

    if jax.default_backend() == "tpu" and pallas_available():
        return "pallas"
    return "xla"


def resolve_impl(impl):
    if impl is None:
        return default_impl()
    if impl not in IMPLS:
        raise ValueError(
            f"merge_impl must be one of {IMPLS} or None (auto), "
            f"got {impl!r}"
        )
    if impl.startswith("pallas") and not pallas_available():
        raise ValueError(
            f"merge_impl={impl!r} requires jax.experimental.pallas; "
            "this jax build lacks it — use merge_impl='xla'"
        )
    return impl


# -- 2-limb key compares ---------------------------------------------------


def _lt(ah, al, bh, bl):
    """(ah, al) < (bh, bl) lexicographic."""
    return (ah < bh) | ((ah == bh) & (al < bl))


def _le(ah, al, bh, bl):
    return (ah < bh) | ((ah == bh) & (al <= bl))


# -- XLA fallback ----------------------------------------------------------


def _count_in_sorted(a_lo, a_hi, q_lo, q_hi, strict: bool):
    """Per query, how many A keys compare {<, <=} it — a vectorized
    2-limb binary search (the log₂ V unrolled gather ladder; 1-D lane
    ops only, pinned by the lint's merge:xla trace)."""
    import jax.numpy as jnp

    Na = a_lo.shape[0]
    nq = q_lo.shape[0]
    lo = jnp.zeros(nq, jnp.uint32)
    hi = jnp.full(nq, Na, jnp.uint32)
    for _ in range(max(1, int(Na).bit_length())):
        mid = (lo + hi) >> 1
        am_lo = a_lo[mid]
        am_hi = a_hi[mid]
        if strict:
            go_right = _lt(am_hi, am_lo, q_hi, q_lo)
        else:
            go_right = _le(am_hi, am_lo, q_hi, q_lo)
        upd = lo < hi
        lo = jnp.where(upd & go_right, mid + jnp.uint32(1), lo)
        hi = jnp.where(upd & ~go_right, mid, hi)
    return lo


def _member_xla(a_lo, a_hi, q_lo, q_hi):
    import jax.numpy as jnp

    Na = a_lo.shape[0]
    if Na == 0:
        return jnp.zeros(q_lo.shape[0], bool)
    cnt = _count_in_sorted(a_lo, a_hi, q_lo, q_hi, strict=True)
    idx = jnp.minimum(cnt, jnp.uint32(Na - 1))
    return (
        (a_lo[idx] == q_lo) & (a_hi[idx] == q_hi)
        & (cnt < jnp.uint32(Na))
    )


def _merge_xla(a_lo, a_hi, b_lo, b_hi):
    """Sorted merge with NO sort: B-side destinations by binary
    search (B is the small side — the ≤F winner block), then one
    M-row flag scatter + cumsum + two gathers assemble the output."""
    import jax.numpy as jnp

    Na, Nb = a_lo.shape[0], b_lo.shape[0]
    if Nb == 0:
        return a_lo, a_hi
    if Na == 0:
        return b_lo, b_hi
    M = Na + Nb
    cnt_le = _count_in_sorted(a_lo, a_hi, b_lo, b_hi, strict=False)
    # strictly increasing (j + #A<=b_j), so the scatter is collision-
    # free and every destination is < M.
    dest_b = jnp.arange(Nb, dtype=jnp.uint32) + cnt_le
    from_b = (
        jnp.zeros(M, jnp.uint32)
        .at[dest_b]
        .set(jnp.uint32(1), unique_indices=True)
    )
    k = jnp.cumsum(from_b, dtype=jnp.uint32)  # inclusive B-rank
    is_b = from_b != 0
    bi = jnp.minimum(
        jnp.maximum(k, jnp.uint32(1)) - jnp.uint32(1),
        jnp.uint32(Nb - 1),
    )
    ai = jnp.minimum(
        jnp.arange(M, dtype=jnp.uint32) - k, jnp.uint32(Na - 1)
    )
    return (
        jnp.where(is_b, b_lo[bi], a_lo[ai]),
        jnp.where(is_b, b_hi[bi], a_hi[ai]),
    )


# -- Merge Path partition (shared by both Pallas kernels) ------------------


def merge_path_starts(a_lo, a_hi, b_lo, b_hi, block: int):
    """``int32[G + 1]`` A-side splits of the merged sequence at every
    ``block``-row output boundary (G = ceil((Na+Nb)/block)): output
    tile ``g`` is the merge of ``A[starts[g]:starts[g+1]]`` with
    ``B[g*block - starts[g] : (g+1)*block - starts[g+1]]``, each range
    at most ``block`` wide. Ties split A-first (the stable "visited
    wins" order). Plain XLA — G+1 parallel diagonal binary searches."""
    import jax.numpy as jnp

    Na, Nb = int(a_lo.shape[0]), int(b_lo.shape[0])
    M = Na + Nb
    G = max(1, -(-M // block))
    d = jnp.minimum(jnp.arange(G + 1, dtype=jnp.int32) * block, M)
    lo = jnp.maximum(d - Nb, 0)
    hi = jnp.minimum(d, Na)
    for _ in range(max(1, int(Na).bit_length() + 1)):
        mid = (lo + hi) >> 1
        ai = jnp.clip(mid, 0, max(Na - 1, 0))
        bj = jnp.clip(d - mid - 1, 0, max(Nb - 1, 0))
        # split <= mid  <=>  b[d-mid-1] merges before a[mid]
        p = _lt(b_hi[bj], b_lo[bj], a_hi[ai], a_lo[ai])
        upd = lo < hi
        hi = jnp.where(upd & p, mid, hi)
        lo = jnp.where(upd & ~p, mid + 1, lo)
    return lo


# -- Pallas kernels --------------------------------------------------------


def _merge_kernel(starts_ref, a_ref, b_ref, out_ref, *, block, M):
    """One output tile of the streaming merge: rank-based block merge
    of the tile's A/B windows. Validity masks (not sentinel rewrites)
    keep out-of-range window rows from counting; their computed ranks
    land >= the tile's real row count by the Merge Path bounds, so the
    one-hot assembly never aliases a live output row."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    g = pl.program_id(0)
    d0 = g * block
    a_s = starts_ref[g]
    a_e = starts_ref[g + 1]
    b_s = d0 - a_s
    a_cnt = a_e - a_s
    rows = jnp.minimum(M - d0, block)
    b_cnt = rows - a_cnt
    aw = a_ref[:, pl.ds(a_s, block)]
    bw = b_ref[:, pl.ds(b_s, block)]
    iot = jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)[:, 0]
    a_ok = iot < a_cnt
    b_ok = iot < b_cnt
    # ranks: A-first on ties (strict compare counts B before A,
    # inclusive compare counts A before-or-at B)
    b_lt_a = _lt(bw[1][None, :], bw[0][None, :],
                 aw[1][:, None], aw[0][:, None]) & b_ok[None, :]
    a_le_b = _le(aw[1][:, None], aw[0][:, None],
                 bw[1][None, :], bw[0][None, :]) & a_ok[:, None]
    pos_a = iot + jnp.sum(b_lt_a, axis=1, dtype=jnp.int32)
    pos_b = iot + jnp.sum(a_le_b, axis=0, dtype=jnp.int32)
    oh_a = (pos_a[:, None] == iot[None, :]) & a_ok[:, None]
    oh_b = (pos_b[:, None] == iot[None, :]) & b_ok[:, None]
    z = jnp.uint32(0)
    for lane in range(2):
        merged = jnp.sum(
            jnp.where(oh_a, aw[lane][:, None], z), axis=0,
            dtype=jnp.uint32,
        ) + jnp.sum(
            jnp.where(oh_b, bw[lane][:, None], z), axis=0,
            dtype=jnp.uint32,
        )
        covered = jnp.sum(
            oh_a.astype(jnp.uint32), axis=0, dtype=jnp.uint32
        ) + jnp.sum(oh_b.astype(jnp.uint32), axis=0, dtype=jnp.uint32)
        out_ref[lane, :] = jnp.where(
            covered > 0, merged, jnp.uint32(_SENT)
        )


def _member_kernel(starts_ref, a_ref, q_ref, out_ref, *, block, M):
    """One merged tile's membership bits: a query matches iff an equal
    A key sits in the tile's A window or is the window's immediate
    predecessor ``A[a_s - 1]`` (ties order A-first, so the equal A key
    — A is sorted — is the nearest A at or before the query's merge
    position; Merge Path puts it no earlier than one element left of
    the window). The ``block``-wide store past the tile's true query
    range is overwritten by the later tiles that own those queries —
    correct under the sequential grid order."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    g = pl.program_id(0)
    d0 = g * block
    a_s = starts_ref[g]
    a_e = starts_ref[g + 1]
    q_s = d0 - a_s
    a_cnt = a_e - a_s
    aw = a_ref[:, pl.ds(a_s, block)]
    qw = q_ref[:, pl.ds(q_s, block)]
    halo = jnp.maximum(a_s - 1, 0)
    h_lo = a_ref[0, halo]
    h_hi = a_ref[1, halo]
    iot = jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)[:, 0]
    a_ok = iot < a_cnt
    eq = (
        (aw[0][:, None] == qw[0][None, :])
        & (aw[1][:, None] == qw[1][None, :])
        & a_ok[:, None]
    )
    mem = jnp.any(eq, axis=0) | (
        (a_s > 0) & (h_lo == qw[0]) & (h_hi == qw[1])
    )
    out_ref[0, pl.ds(q_s, block)] = mem.astype(jnp.uint32)


def _pad_soa(lo, hi, pad_to: int):
    import jax.numpy as jnp

    n = lo.shape[0]
    out = jnp.full((2, pad_to), _SENT, jnp.uint32)
    out = out.at[0, :n].set(lo).at[1, :n].set(hi)
    return out


def _merge_pallas(a_lo, a_hi, b_lo, b_hi, block, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    Na, Nb = int(a_lo.shape[0]), int(b_lo.shape[0])
    M = Na + Nb
    if Nb == 0:
        return a_lo, a_hi
    if Na == 0:
        return b_lo, b_hi
    G = max(1, -(-M // block))
    starts = merge_path_starts(a_lo, a_hi, b_lo, b_hi, block)
    a = _pad_soa(a_lo, a_hi, Na + block)
    b = _pad_soa(b_lo, b_hi, Nb + block)
    out = pl.pallas_call(
        partial(_merge_kernel, block=block, M=M),
        grid=(G,),
        in_specs=[
            pl.BlockSpec(starts.shape, lambda g: (0,)),
            pl.BlockSpec(a.shape, lambda g: (0, 0)),
            pl.BlockSpec(b.shape, lambda g: (0, 0)),
        ],
        out_specs=pl.BlockSpec((2, block), lambda g: (0, g)),
        out_shape=jax.ShapeDtypeStruct((2, G * block), jnp.uint32),
        interpret=interpret,
    )(starts, a, b)
    return out[0, :M], out[1, :M]


def _member_pallas(a_lo, a_hi, q_lo, q_hi, block, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    Na, Nq = int(a_lo.shape[0]), int(q_lo.shape[0])
    if Nq == 0:
        return jnp.zeros(0, bool)
    if Na == 0:
        return jnp.zeros(Nq, bool)
    M = Na + Nq
    G = max(1, -(-M // block))
    starts = merge_path_starts(a_lo, a_hi, q_lo, q_hi, block)
    a = _pad_soa(a_lo, a_hi, Na + block)
    q = _pad_soa(q_lo, q_hi, Nq + block)
    out = pl.pallas_call(
        partial(_member_kernel, block=block, M=M),
        grid=(G,),
        in_specs=[
            pl.BlockSpec(starts.shape, lambda g: (0,)),
            pl.BlockSpec(a.shape, lambda g: (0, 0)),
            pl.BlockSpec(q.shape, lambda g: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Nq + block), lambda g: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, Nq + block), jnp.uint32),
        interpret=interpret,
    )(starts, a, q)
    return out[0, :Nq] != 0


# -- public entry points ---------------------------------------------------


def member_sorted(a_lo, a_hi, q_lo, q_hi, *, impl: str = "xla",
                  block: int = DEFAULT_BLOCK):
    """``bool[Nq]``: for each sorted query key, whether it occurs in
    the sorted array A. Exact multiset semantics (sentinel queries
    match A's sentinel tail; callers mask)."""
    if impl == "xla":
        return _member_xla(a_lo, a_hi, q_lo, q_hi)
    return _member_pallas(
        a_lo, a_hi, q_lo, q_hi, block,
        interpret=(impl == "pallas_interpret"),
    )


def merge_sorted(a_lo, a_hi, b_lo, b_hi, *, impl: str = "xla",
                 block: int = DEFAULT_BLOCK):
    """``(lo[Na+Nb], hi[Na+Nb])``: the sorted merge of two sorted
    2-limb key arrays, A-first on ties; sentinel tails merge to the
    tail. No O(Na)-row sort on either implementation."""
    if impl == "xla":
        return _merge_xla(a_lo, a_hi, b_lo, b_hi)
    return _merge_pallas(
        a_lo, a_hi, b_lo, b_hi, block,
        interpret=(impl == "pallas_interpret"),
    )


def compact_winners(is_new, pos, lo, hi, nf: int, *, impl: str):
    """``(nf_pos[nf], w_lo[nf], w_hi[nf])``: the winner rows
    (``is_new``) of the key-sorted candidate arrays, compacted
    order-preserving to the first ``nf`` rows and sentinel-padded —
    winners stay in KEY order, the order the engines' fetch gather,
    parent-log append, and visited merge all share.

    Implementation-adaptive like the streaming passes: ``xla`` (the
    CPU fallback) uses an O(B) rank scatter — collision-free
    destinations from an inclusive-rank cumsum, non-winners routed
    past the output and dropped — which on CPU replaces the 4-lane
    B-row compaction sort that was the fallback path's single
    costliest dedup stage (736 ms/wave at paxos-4 shapes, PERF.md
    §merge-kernel). The ``pallas`` impls keep the 4-lane stable sort:
    TPU scatters serialize, B-scale sorts do not."""
    import jax.numpy as jnp
    from jax import lax

    B = pos.shape[0]
    if impl == "xla":
        rank = jnp.cumsum(is_new.astype(jnp.uint32))
        # winner dests rank-1 are unique in [0, B); non-winners are
        # routed to unique slots in [B, 2B). Both tails land past the
        # nf-row output and drop (nf <= B), so indices stay globally
        # unique — the contract `unique_indices` asserts.
        dest = jnp.where(
            is_new,
            rank - jnp.uint32(1),
            jnp.uint32(B) + jnp.arange(B, dtype=jnp.uint32),
        )
        out = jnp.full((3, nf), _SENT, jnp.uint32)
        out = out.at[:, dest].set(
            jnp.stack([pos, lo, hi]),
            mode="drop", unique_indices=True,
        )
        return out[0], out[1], out[2]
    okey = jnp.where(
        is_new,
        jnp.arange(B, dtype=jnp.uint32),
        jnp.uint32(_SENT),
    )
    _, nf_pos, w_lo, w_hi = lax.sort((okey, pos, lo, hi), num_keys=1)
    # rows past the winner count carry arbitrary non-winner lanes
    # after the sort; sentinel them like the scatter path does.
    valid = jnp.arange(nf, dtype=jnp.uint32) < jnp.sum(
        is_new, dtype=jnp.uint32
    )
    s = jnp.uint32(_SENT)
    return (
        jnp.where(valid, nf_pos[:nf], s),
        jnp.where(valid, w_lo[:nf], s),
        jnp.where(valid, w_hi[:nf], s),
    )
