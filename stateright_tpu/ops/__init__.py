"""Device kernels for the TPU wave engine.

The perf-critical inner ops of the reference's checkers — state
fingerprinting (src/lib.rs:329-375), the concurrent visited set
(bfs.rs:28-29 DashMap), and frontier queue management
(job_market.rs) — re-designed as vectorized XLA ops over ``uint32``
lanes: limb-based 64-bit hashing, a device-resident open-addressing
hash set with batched scatter-claim insertion, and mask/scan
compaction.
"""

from .u64 import U64, u64_add, u64_mul, u64_shr, u64_xor
from .fingerprint import fingerprint_u32v, splitmix64
from .hashset import DeviceHashSet
from .bitmask import (
    bit_select,
    mask_to_words,
    mask_words,
    pack_bits_host,
    popcount_words,
    words_to_mask,
)

__all__ = [
    "U64",
    "u64_add",
    "u64_mul",
    "u64_shr",
    "u64_xor",
    "fingerprint_u32v",
    "splitmix64",
    "DeviceHashSet",
    "bit_select",
    "mask_to_words",
    "mask_words",
    "pack_bits_host",
    "popcount_words",
    "words_to_mask",
]
