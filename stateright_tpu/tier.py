"""Tiered visited set: the host-DRAM COLD tier behind the device-hot
sort-merge dedup (ROADMAP direction 1b, PERF.md §tiered-visited).

Every engine before this round kept the ENTIRE visited set
device-resident, so the reachable space was bounded by HBM — the
memplan capacity projection prices exactly when that breaks (44 MB at
paxos-4's next v-class, §memory), and GPUexplore's scalability study
(arXiv:1801.05857) frames dedup-structure capacity, not step
throughput, as what caps explicit-state exploration. The elastic-
resource framing of arXiv:1203.6806 is the fix this module implements:
the visited set becomes TWO tiers —

* **HOT** — the existing incrementally-sorted ``vkeys`` prefix on
  device, now capped by a ladder ceiling (``tier_hot_rows``; the
  memplan projection decides the split in ``"auto"`` mode via
  :func:`stateright_tpu.memplan.decide_hot_rows`). The wave's
  on-device membership/merge passes are unchanged and scale with the
  HOT count, not the cumulative unique count.
* **COLD** — sorted immutable runs in host DRAM (this module's
  :class:`ColdStore`). A spill moves the whole hot prefix — ALREADY
  ``(hi, lo)``-lexsorted by the round-10 invariant, so a spilled run
  needs no host sort — at the existing per-chunk sync (the stats
  readback just blocked; the prefix download piggybacks exactly the
  way the checkpoint carry download does: transfer, not a new sync
  point). Run ingest and compaction happen on a WORKER THREAD,
  overlapped with the next dispatch's device compute; membership
  joins the worker (:meth:`ColdStore.sync`) before it reads.

**Exactness: the deferred-commit protocol.** With a non-empty cold
tier, a candidate that survives the on-device hot merge is only
*provisionally* new — it might duplicate a spilled key. The engines
therefore switch to a tiered chunk program (one wave per dispatch)
whose wave STAGES its winners (keys, states, ebits, parent limbs)
instead of committing them, and whose NEXT dispatch takes a host-
computed ``keep`` mask — the batched sort-merge membership verdict of
this module's binary search over the cold runs — and commits only the
survivors: count, frontier, parent log, and the hot-tier merge all
see exactly the truly-new rows, in the same key-sorted order the
resident engine commits, so per-wave counters, unique totals, and
counterexample paths are bit-identical to an all-resident run
(``pytest -m tier`` pins it; trace_diff proves it on the committed
forced-spill artifacts). No false-new row is ever expanded, so there
is nothing to retract — the membership pass retires false-new rows
BEFORE they reach the unique counts or the parent-log drain.

Runs are disjoint by construction: a key is spilled at most once
(a cold member never passes the keep mask, so it never re-enters the
hot tier), which makes ``hot + sum(run rows)`` the exact cumulative
unique count and the per-run binary searches an exact membership
oracle. When the run count passes ``max_runs`` the worker compacts
all runs into one (one ``np.sort`` over the packed u64 keys) so
membership stays O(log) per query with a bounded run fan-in.

Import-light by design (numpy + stdlib only): the device side lives
in the engines (checkers/tpu_sortmerge.py, parallel/
engine_sortmerge.py), snapshots in checkpoint.py, pricing in
memplan.py.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

_SENT = 0xFFFFFFFF

#: logical bytes per cold-tier key: two uint32 limbs.
COLD_BYTES_PER_ROW = 8


def pack_u64(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    """One sortable uint64 key per (lo, hi) limb pair, ordered the
    SAME way as the device invariant's ``(hi, lo)`` lexsort — hi is
    the major limb — so a ``(hi, lo)``-lexsorted run packs to a
    sorted u64 array with no re-sort."""
    return (
        hi.astype(np.uint64) << np.uint64(32)
    ) | lo.astype(np.uint64)


def member_mask(run: np.ndarray, q: np.ndarray) -> np.ndarray:
    """``bool[len(q)]``: which packed query keys appear in the sorted
    packed run (one vectorized binary search — the batched sort-merge
    membership primitive)."""
    if run.size == 0 or q.size == 0:
        return np.zeros(q.shape, bool)
    idx = np.searchsorted(run, q)
    idx = np.minimum(idx, run.size - 1)
    return run[idx] == q


class ColdStore:
    """The host-DRAM cold tier: per-shard lists of sorted immutable
    runs (packed u64 keys), with async ingest and run compaction on a
    worker thread.

    Per-shard because spills are per-shard (each mesh shard owns the
    keys with ``fp_lo % S == shard``) and membership queries are too
    — a shard's provisional winners can only duplicate keys the SAME
    shard spilled. Single-chip engines are the ``n_shards=1`` case.
    """

    def __init__(self, n_shards: int = 1, max_runs: int = 8):
        self.n_shards = int(n_shards)
        self.max_runs = int(max_runs)
        #: per-shard list of sorted np.uint64 arrays (immutable runs)
        self.runs: list[list[np.ndarray]] = [
            [] for _ in range(self.n_shards)
        ]
        self.spills = 0
        self._lock = threading.Lock()
        self._worker: Optional[threading.Thread] = None
        #: wall seconds spent in worker-side ingest/compaction (the
        #: overlapped cost — tier_spill events report it)
        self.ingest_sec = 0.0

    # -- ingest (the spill path) ------------------------------------------

    def ingest(self, per_shard: list[tuple[np.ndarray, np.ndarray]],
               *, asynchronous: bool = True) -> None:
        """Append one spill — per-shard ``(lo, hi)`` limb pairs, each
        ALREADY (hi, lo)-lexsorted (the device prefix invariant) — as
        new immutable runs. ``asynchronous=True`` runs the pack +
        compaction on a worker thread so it overlaps the next
        dispatch's device compute; :meth:`sync` joins it before any
        membership read. At most one ingest is in flight (the double-
        buffer discipline: the caller spills at chunk syncs, which
        are strictly ordered)."""
        self.sync()
        packed = [
            (np.ascontiguousarray(lo), np.ascontiguousarray(hi))
            for lo, hi in per_shard
        ]
        self.spills += 1
        if not asynchronous:
            self._do_ingest(packed)
            return
        t = threading.Thread(
            target=self._do_ingest, args=(packed,),
            name="stpu-tier-ingest", daemon=True,
        )
        self._worker = t
        t.start()

    def _do_ingest(self, per_shard) -> None:
        import time

        t0 = time.monotonic()
        for s, (lo, hi) in enumerate(per_shard):
            if lo.size == 0:
                continue
            run = pack_u64(lo, hi)
            with self._lock:
                self.runs[s].append(run)
                if len(self.runs[s]) > self.max_runs:
                    # compaction: one k-way sort-merge (np.sort over
                    # the concat — runs are disjoint, so no dedup
                    # pass is needed) bounds the membership fan-in
                    merged = np.sort(np.concatenate(self.runs[s]))
                    self.runs[s] = [merged]
        self.ingest_sec += time.monotonic() - t0

    def sync(self) -> None:
        """Join any in-flight ingest (call before membership reads
        and before snapshotting the run set)."""
        w = self._worker
        if w is not None and w.is_alive():
            w.join()
        self._worker = None

    # -- membership (the batched sort-merge pass) -------------------------

    def member(self, shard: int, q_lo: np.ndarray,
               q_hi: np.ndarray) -> np.ndarray:
        """``bool[len(q)]``: which query keys one shard's cold runs
        contain — the host half of the tiered dedup. Queries are the
        wave's provisional winner keys; the engines invert this into
        the ``keep`` mask the commit dispatch consumes."""
        q = pack_u64(q_lo, q_hi)
        out = np.zeros(q.shape, bool)
        with self._lock:
            runs = list(self.runs[shard])
        for run in runs:
            out |= member_mask(run, q)
        return out

    # -- accounting --------------------------------------------------------

    def rows(self) -> int:
        with self._lock:
            return int(sum(
                r.size for shard in self.runs for r in shard
            ))

    def shard_rows(self) -> list[int]:
        with self._lock:
            return [
                int(sum(r.size for r in shard)) for shard in self.runs
            ]

    def bytes(self) -> int:
        return self.rows() * COLD_BYTES_PER_ROW

    def run_count(self) -> int:
        with self._lock:
            return sum(len(shard) for shard in self.runs)

    def summary(self) -> dict:
        """The accounting block tier_spill events, the memory
        watermark, and checkpoint manifests embed. The tracer→metrics
        bridge counts the emitted ``tier_spill`` events into
        ``stpu_tier_spills_total`` (stateright_tpu/metrics.py), so a
        resident service's spill pressure reads live on
        ``GET /.metrics``."""
        return dict(
            n_shards=self.n_shards,
            spills=int(self.spills),
            runs=self.run_count(),
            cold_rows_total=self.rows(),
            cold_bytes_total=self.bytes(),
            rows_per_shard=self.shard_rows(),
            ingest_sec=round(self.ingest_sec, 6),
        )

    # -- snapshot / re-shard ----------------------------------------------

    def snapshot_runs(self) -> list[list[tuple[np.ndarray, np.ndarray]]]:
        """Per-shard ``(lo, hi)`` limb pairs of every run (for
        checkpoint serialization — checkpoint.py stores them as
        ``tier_run{shard}_{i}_lo/hi`` buffers)."""
        self.sync()
        out = []
        with self._lock:
            for shard in self.runs:
                out.append([
                    (
                        (r & np.uint64(0xFFFFFFFF)).astype(np.uint32),
                        (r >> np.uint64(32)).astype(np.uint32),
                    )
                    for r in shard
                ])
        return out

    @classmethod
    def from_runs(cls, per_shard_runs, max_runs: int = 8,
                  spills: int = 0) -> "ColdStore":
        """Rebuild a store from snapshot runs (per-shard lists of
        ``(lo, hi)`` pairs, each (hi, lo)-lexsorted)."""
        store = cls(n_shards=len(per_shard_runs), max_runs=max_runs)
        store.spills = int(spills)
        for s, shard in enumerate(per_shard_runs):
            for lo, hi in shard:
                if np.asarray(lo).size:
                    store.runs[s].append(
                        pack_u64(np.asarray(lo, np.uint32),
                                 np.asarray(hi, np.uint32))
                    )
        return store

    def repartitioned(self, n_shards_new: int,
                      max_runs: Optional[int] = None) -> "ColdStore":
        """The cold half of the elastic re-shard (checkpoint.py): each
        run splits by the NEW owner function ``lo % S_new`` — the same
        (owner, fp) seam the mesh routing sort and the resident
        re-shard use. Filtering a sorted run preserves its order, so
        every piece is still a sorted immutable run; runs stay
        disjoint because they were disjoint globally."""
        self.sync()
        out = ColdStore(
            n_shards=n_shards_new,
            max_runs=self.max_runs if max_runs is None else max_runs,
        )
        out.spills = self.spills
        S = np.uint64(max(n_shards_new, 1))
        with self._lock:
            for shard in self.runs:
                for run in shard:
                    owner = (run & np.uint64(0xFFFFFFFF)) % S
                    for d in range(n_shards_new):
                        piece = run[owner == np.uint64(d)]
                        if piece.size:
                            out.runs[d].append(piece)
        return out
