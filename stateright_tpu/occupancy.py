"""The one home of occupancy/headroom warning text.

Three consumers watch a fixed-capacity device buffer fill up and want
the same warning shape — "X% full (n/cap); what breaks past the
cliff, which knob to turn":

* the hash-table engine's visited-table watch
  (checkers/tpu.py ``_maybe_warn_occupancy`` — open addressing
  degrades before it overflows, so it warns at 70%),
* the per-shard visited-occupancy metric of the mesh observability
  layer (telemetry.shard_balance / tools/shard_report.py — the
  sorted arrays are exact-capacity, so the watch is overflow
  headroom, not probe pressure),
* the routed dest-tile fill watch (same report — ``all_to_all``
  correctness depends on every destination run fitting its lossless
  ``Bd`` tile, so fill approaching the cap is the signal that the
  next skewed wave trips ``c_overflow``).

Each used to (or would) carry its own f-string; this module is the
shared formatter so the message, the threshold semantics, and the
"which knob" pointer can't drift per consumer. Import-light by
design: tools and telemetry read traces without jax.
"""

from __future__ import annotations

from typing import Optional

#: the hash-table engine's probe-pressure threshold (open addressing
#: degrades well before it is full).
PROBE_PRESSURE_THRESHOLD = 0.7

#: headroom threshold for EXACT-capacity buffers (the sorted visited
#: arrays, the routed dest tiles): nothing degrades before 100%, but
#: past this fill one skewed wave can overflow.
HEADROOM_THRESHOLD = 0.8


def occupancy_warning(
    occupancy: float,
    *,
    kind: str = "visited table",
    threshold: float = PROBE_PRESSURE_THRESHOLD,
    used: Optional[int] = None,
    capacity: Optional[int] = None,
    bytes_per_row: Optional[int] = None,
    consequence: str = (
        "probe failures become likely past ~85% — consider a larger "
        "capacity"
    ),
) -> Optional[str]:
    """The shared warning line, or None while ``occupancy`` is at or
    under ``threshold``. ``used``/``capacity`` add the absolute
    counts; ``bytes_per_row`` (the resident-buffer ledger's per-entry
    cost, round 12) additionally prices them — the warning then says
    what the fill *weighs* and what the full buffer would, so the
    capacity decision is a memory decision, not just a row count;
    ``consequence`` names what breaks and which knob fixes it."""
    if occupancy <= threshold:
        return None
    detail = (
        f" ({used}/{capacity})"
        if used is not None and capacity is not None
        else ""
    )
    if (bytes_per_row is not None and used is not None
            and capacity is not None):
        # ONE byte formatter repo-wide (memplan.format_bytes — the
        # same rendering mem_report uses; numpy-only, still no jax)
        from .memplan import format_bytes

        detail += (
            f" [{format_bytes(used * bytes_per_row)} of "
            f"{format_bytes(capacity * bytes_per_row)}]"
        )
    return f"{kind} {occupancy:.0%} full{detail}; {consequence}"
