"""stateright_tpu: a TPU-native model-checking framework.

A brand-new framework with the capabilities of stateright
(Rust reference surveyed in SURVEY.md): exhaustive BFS/DFS/on-demand/
simulation checking of nondeterministic models with always/sometimes/
eventually properties, counterexample paths, symmetry reduction, an
actor framework with pluggable network semantics and a real UDP
runtime, linearizability/sequential-consistency testers, and a web
Explorer — re-designed for TPUs: model states compile to fixed-width
vectors, and the BFS frontier-expansion loop runs as vmapped/sharded
XLA kernels with all-to-all frontier shuffles across a device mesh
(see stateright_tpu.checkers.tpu and stateright_tpu.parallel).
"""

from .model import Model, Property, Expectation
from .fingerprint import fingerprint, stable_hash
from .checker import CheckerBuilder, Checker, DiscoveryClassification
from .path import Path
from .report import Reporter, WriteReporter, ReportData
from .visitor import CheckerVisitor, PathRecorder, StateRecorder
from .utils import HashableMap, HashableSet, DenseNatMap, VectorClock

__version__ = "0.1.0"

__all__ = [
    "Model",
    "Property",
    "Expectation",
    "fingerprint",
    "stable_hash",
    "CheckerBuilder",
    "Checker",
    "DiscoveryClassification",
    "Path",
    "Reporter",
    "WriteReporter",
    "ReportData",
    "CheckerVisitor",
    "PathRecorder",
    "StateRecorder",
    "HashableMap",
    "HashableSet",
    "DenseNatMap",
    "VectorClock",
]
