"""Checker configuration and the common checker interface.

``CheckerBuilder`` mirrors the reference's fluent builder
(stateright src/checker.rs:64-267): configure symmetry, bounded targets,
worker count and visitors, then spawn a specific engine. ``Checker``
mirrors the result interface (src/checker.rs:273-557): counts,
discoveries as replayable :class:`~stateright_tpu.path.Path` objects,
reporting, and assertion helpers for tests.

Departures from the reference, by design:

* Host engines run the search *synchronously* on first demand (``join``
  or any accessor) instead of spawning OS threads — Python threads
  cannot parallelize this CPU-bound loop. Parallelism lives in the TPU
  engine (``spawn_tpu``), where a whole frontier wave is one device
  program and scale-out is a sharded mesh, replacing the reference's
  thread pool + work-stealing job market (src/job_market.rs).
* ``threads(n)`` drives a real worker pool in the host BFS (1,500-state
  work-share blocks over a shared pending deque, mirroring the
  reference's job-market granularity) — though CPython's GIL keeps
  pure-Python model callbacks serialized, so wall-clock gains are
  bounded by the callbacks' native time (hashing, dataclass compare).
"""

from __future__ import annotations

import time
from enum import Enum
from typing import Any, Callable, Optional, Sequence

from .model import Expectation, Model, Property, State
from .path import Path
from .report import ReportData, Reporter
from .visitor import CheckerVisitor, as_visitor


class DiscoveryClassification(str, Enum):
    """Whether a discovery proves or refutes a property (checker.rs:38-52)."""

    EXAMPLE = "example"
    COUNTEREXAMPLE = "counterexample"


class CheckerBuilder:
    """Fluent checker configuration (checker.rs:64-267)."""

    def __init__(self, model: Model):
        self.model = model
        self._symmetry: Optional[Callable[[State], State]] = None
        self._target_state_count: Optional[int] = None
        self._target_max_depth: Optional[int] = None
        self._threads: int = 1
        self._visitor: Optional[CheckerVisitor] = None
        self._unsound_ok: bool = False

    def unsound_ok(self) -> "CheckerBuilder":
        """Waive the reduction soundness-certificate gates
        (analysis/soundness.py): a declared ``DeviceRewriteSpec`` or
        ample mask that FAILS its obligations runs anyway instead of
        refusing at spawn. Research escape hatch (``--unsound-ok`` on
        the CLI) — the run's counts carry no soundness guarantee."""
        self._unsound_ok = True
        return self

    def symmetry(self) -> "CheckerBuilder":
        """Enable symmetry reduction via the state's own ``representative``
        method (checker.rs:217-222)."""
        return self.symmetry_fn(lambda state: state.representative())

    def symmetry_fn(self, f: Callable[[State], State]) -> "CheckerBuilder":
        """Enable symmetry reduction with an explicit representative
        function (checker.rs:225-232)."""
        self._symmetry = f
        return self

    def target_state_count(self, count: int) -> "CheckerBuilder":
        """Stop after visiting approximately ``count`` unique states
        (checker.rs:236-241)."""
        self._target_state_count = count
        return self

    def target_max_depth(self, depth: int) -> "CheckerBuilder":
        """Do not expand states deeper than ``depth`` (checker.rs:244-249)."""
        self._target_max_depth = depth
        return self

    def threads(self, n: int) -> "CheckerBuilder":
        """Worker threads for the host BFS (checker.rs:253-258):
        ``spawn_bfs`` runs n workers over the shared pending deque in
        1,500-state blocks (the reference's work-share granularity).
        Counts and the discovered property SET match the sequential
        run; which state discovers a property can differ run-to-run,
        as in the reference's thread race. Note CPython's GIL: on
        pure-Python models this is parity, not speedup — device
        engines (spawn_tpu*) are the parallelism story here."""
        self._threads = n
        return self

    def visitor(self, v) -> "CheckerBuilder":
        """Attach a visitor called with every evaluated state's path
        (checker.rs:261-266)."""
        self._visitor = as_visitor(v)
        return self

    # -- spawn methods (checker.rs:157-212) ------------------------------

    def spawn_bfs(self) -> "Checker":
        from .checkers.bfs import BfsChecker

        return BfsChecker(self)

    def spawn_dfs(self) -> "Checker":
        from .checkers.dfs import DfsChecker

        return DfsChecker(self)

    def spawn_simulation(self, seed: int = 0, chooser=None) -> "Checker":
        from .checkers.simulation import SimulationChecker, UniformChooser

        return SimulationChecker(self, chooser or UniformChooser(), seed)

    def spawn_on_demand(self) -> "Checker":
        from .checkers.on_demand import OnDemandChecker

        return OnDemandChecker(self)

    def spawn_tpu(self, **kwargs) -> "Checker":
        """Spawn the TPU wave engine — the reference's ``spawn_bfs``
        re-imagined for an accelerator (see BASELINE.json north star)."""
        from .checkers.tpu import TpuBfsChecker

        return TpuBfsChecker(self, **kwargs)

    def spawn_tpu_sortmerge(self, **kwargs) -> "Checker":
        """Spawn the sort-merge wave engine: visited set as a sorted
        fingerprint array merged per wave, no scatters in the hot loop
        — the TPU-idiomatic dedup (see checkers/tpu_sortmerge.py)."""
        from .checkers.tpu_sortmerge import SortMergeTpuBfsChecker

        return SortMergeTpuBfsChecker(self, **kwargs)

    def spawn_tpu_sharded(self, **kwargs) -> "Checker":
        """Spawn the multi-chip wave engine: the frontier and visited
        set sharded over a ``jax.sharding.Mesh``, with per-wave
        all-to-all frontier shuffles replacing the reference's
        work-stealing job market (src/job_market.rs). Owner-local
        dedup uses the hash table; prefer
        :meth:`spawn_tpu_sharded_sortmerge` on real TPU hardware."""
        from .parallel import ShardedTpuBfsChecker

        return ShardedTpuBfsChecker(self, **kwargs)

    def spawn_tpu_simulation(self, **kwargs) -> "Checker":
        """Spawn the device simulation checker: N parallel random walks
        under vmap, advancing in lockstep inside one jitted loop — the
        accelerator re-design of the reference's simulation checker
        (see checkers/tpu_simulation.py for semantics deltas)."""
        from .checkers.tpu_simulation import TpuSimulationChecker

        return TpuSimulationChecker(self, **kwargs)

    def spawn_hybrid(self, **kwargs) -> "Checker":
        """Spawn the hybrid racer: host DFS in a thread vs the device
        sort-merge engine, first to complete wins and the loser is
        cancelled — TPU-or-tie on shallow bugs, the full device win on
        deep verification (see checkers/hybrid.py). kwargs go to the
        device engine."""
        from .checkers.hybrid import HybridChecker

        return HybridChecker(self, **kwargs)

    def spawn_tpu_sharded_sortmerge(self, **kwargs) -> "Checker":
        """Spawn the multi-chip SORT-MERGE wave engine: the all-to-all
        routing of spawn_tpu_sharded with owner-local dedup on the
        sorted-array fast path the repo benchmarks (PERF.md) — route
        and compact via one (owner, key) sort, merge via stable sorts,
        parent forest as an append-only log. No scatters in the hot
        loop (see parallel/engine_sortmerge.py)."""
        from .parallel import ShardedSortMergeTpuBfsChecker

        return ShardedSortMergeTpuBfsChecker(self, **kwargs)

    def serve(self, addr: str):
        """Serve the Explorer web UI for this model (checker.rs:139-146)."""
        from .explorer.server import serve

        return serve(self, addr)


class Checker:
    """Common checker result interface (checker.rs:273-557)."""

    def __init__(self, builder: CheckerBuilder):
        self.builder = builder
        self.model = builder.model
        self._discoveries: dict[str, Path] = {}
        self._total_states = 0
        self._unique_states = 0
        self._max_depth = 0
        self._done = False
        self._run_error: Optional[Exception] = None
        self._started_at: Optional[float] = None
        self._finished_at: Optional[float] = None

    # -- engine hook -----------------------------------------------------

    def _run(self, reporter: Optional[Reporter] = None) -> None:
        """Run the search to completion. Implemented by engines."""
        raise NotImplementedError

    def _ensure_run(self, reporter: Optional[Reporter] = None) -> None:
        if self._done:
            if self._run_error is not None:
                raise self._run_error
            return
        # Run telemetry (stateright_tpu/telemetry.py): when a tracer
        # is active, every engine's execution is bracketed by
        # run_begin/run_end events here — the one place all engines
        # pass through — so host and device checkers trace alike.
        from . import telemetry

        tracer = telemetry.current_tracer()
        if tracer is not None:
            tracer.begin_run(lane=self._lane_config())
        self._started_at = time.monotonic()
        try:
            self._run(reporter)
        except Exception as exc:
            # A failed run is terminal: remember the error and replay
            # it on every later accessor instead of re-executing the
            # whole search (which would raise the same error again
            # after repaying the full runtime — and, on the TPU
            # engines, would also discard discoveries recorded before
            # an overflow raise).
            self._finished_at = time.monotonic()
            self._done = True
            self._run_error = exc
            if tracer is not None:
                tracer.end_run(
                    error=f"{type(exc).__name__}: {exc}",
                    **self._run_stats(),
                )
            raise
        self._finished_at = time.monotonic()
        self._done = True
        if tracer is not None:
            self._emit_settlement_verdicts(tracer)
            tracer.end_run(error=None, **self._run_stats())

    def _emit_settlement_verdicts(self, tracer) -> None:
        """Round-14 verdict timeline, the exhaustion half: a property
        with NO discovery settles only when the configured search
        completes (the same completion semantics assert_properties
        applies — bounded closures count as complete). Discovery
        verdicts land earlier, at the engines' own settle points (the
        device chunk loop, the host checkers' ``_discover``); this
        run-end sweep covers the rest, so every property of a clean
        run has exactly one ``verdict`` event and time-to-verdict is
        a measured number per property. Error paths skip it (a run
        that raised settled nothing it didn't already emit), and so
        do CANCELLED runs — the hybrid racer's losing side returns
        early with partial results, and a partial search has not
        exhausted anything."""
        if getattr(self, "cancelled", False):
            return
        discovered = set(self._discoveries) | set(
            getattr(self, "_discovered_fps", None) or {}
        )
        metrics = getattr(self, "metrics", None)
        waves = (metrics.get("waves")
                 if isinstance(metrics, dict) else None)
        for prop in self.model.properties():
            if prop.name in discovered:
                continue
            tracer.event(
                "verdict",
                property=prop.name,
                expectation=prop.expectation.name.lower(),
                kind="exhaustion",
                wave=(int(waves) if waves is not None else None),
                depth=self._max_depth,
            )

    def _lane_config(self) -> dict:
        """The run's lane description, embedded in the trace
        run_begin event (engines extend with shapes/budgets)."""
        return dict(
            engine=type(self).__name__,
            model=type(self.model).__name__,
            target_state_count=self.builder._target_state_count,
            target_max_depth=self.builder._target_max_depth,
        )

    def _run_stats(self) -> dict:
        """The run's outcome summary for the trace run_end event."""
        return dict(
            total_states=self._total_states,
            unique_states=self._unique_states,
            max_depth=self._max_depth,
            duration_sec=round(self.duration_sec(), 6),
        )

    # -- status (checker.rs:287-314) -------------------------------------

    def state_count(self) -> int:
        self._ensure_run()
        return self._total_states

    def unique_state_count(self) -> int:
        self._ensure_run()
        return self._unique_states

    def max_depth(self) -> int:
        self._ensure_run()
        return self._max_depth

    def is_done(self) -> bool:
        return self._done

    def join(self) -> "Checker":
        self._ensure_run()
        return self

    def duration_sec(self) -> float:
        if self._started_at is None:
            return 0.0
        end = self._finished_at if self._finished_at is not None else time.monotonic()
        return end - self._started_at

    # -- on-demand hooks (checker.rs:278-285); overridden by OnDemand ----

    def check_fingerprint(self, fp: int) -> None:
        pass

    def run_to_completion(self) -> None:
        self._ensure_run()

    # -- discoveries (checker.rs:287-300) --------------------------------

    def discoveries(self) -> dict[str, Path]:
        self._ensure_run()
        return dict(self._discoveries)

    def discovery(self, name: str) -> Optional[Path]:
        return self.discoveries().get(name)

    def discovery_classification(self, name: str) -> DiscoveryClassification:
        prop = self.model.property_by_name(name)
        if prop.expectation == Expectation.SOMETIMES:
            return DiscoveryClassification.EXAMPLE
        return DiscoveryClassification.COUNTEREXAMPLE

    # -- reporting (checker.rs:330-431) ----------------------------------

    def report(self, reporter: Reporter) -> "Checker":
        self._ensure_run(reporter)
        reporter.report_checking(
            ReportData(
                total_states=self._total_states,
                unique_states=self._unique_states,
                max_depth=self._max_depth,
                duration_sec=self.duration_sec(),
                done=self.is_done(),
            )
        )
        reporter.report_discoveries(self)
        return self

    def join_and_report(self, reporter: Reporter) -> "Checker":
        return self.report(reporter)

    # -- assertion helpers (checker.rs:447-556) --------------------------

    def assert_properties(self) -> None:
        """Assert no always/eventually counterexamples and an example for
        every sometimes property (checker.rs:447-473)."""
        for prop in self.model.properties():
            if prop.expectation == Expectation.SOMETIMES:
                self.assert_any_discovery(prop.name)
            else:
                self.assert_no_discovery(prop.name)

    def assert_any_discovery(self, name: str) -> Path:
        path = self.discovery(name)
        if path is None:
            raise AssertionError(f"expected a discovery for {name!r}")
        return path

    def assert_no_discovery(self, name: str) -> None:
        path = self.discovery(name)
        if path is not None:
            raise AssertionError(
                f"unexpected discovery for {name!r}: {path.encode()}\n{path!r}"
            )

    def assert_discovery(self, name: str, actions: Sequence[Any]) -> None:
        """Assert a discovery exists and matches the given action sequence
        (checker.rs:506-556)."""
        path = self.assert_any_discovery(name)
        if list(path.actions()) != list(actions):
            raise AssertionError(
                f"discovery for {name!r} has actions {path.actions()!r}, "
                f"expected {list(actions)!r}"
            )

    # -- shared engine internals ----------------------------------------

    def _eventually_bits_init(self) -> int:
        """Bitmask with one bit per eventually property, all set.

        Mirrors ``EventuallyBits`` seeding (checker.rs:559-566,
        bfs.rs:61-73): bits clear as conditions are met along a path;
        any bit surviving to a terminal state is a counterexample.
        """
        bits = 0
        for i, prop in enumerate(self.model.properties()):
            if prop.expectation == Expectation.EVENTUALLY:
                bits |= 1 << i
        return bits

    def _properties(self) -> Sequence[Property]:
        return self.model.properties()

    def _all_discovered(self) -> bool:
        """Early-exit condition: every property has a discovery
        (bfs.rs:128-135)."""
        props = self.model.properties()
        return len(props) > 0 and all(
            p.name in self._discoveries for p in props
        )
