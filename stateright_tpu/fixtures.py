"""Shared toy-model fixtures for tests and docs.

Counterparts of the reference's test fixtures
(stateright src/test_util.rs): ``LinearEquation`` (test_util.rs:140-192,
the standard checker fixture), ``BinaryClock`` (test_util.rs:4-47),
``DGraph`` (test_util.rs:50-116, the eventually-semantics fixture), and
``Panicker`` (test_util.rs:195-228, error-propagation fixture).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from .model import Model, Property


@dataclass
class LinearEquation(Model):
    """Find nonneg u8 solutions to ``a*x + b*y == c`` by brute search.

    States are ``(x, y)`` pairs of wrapping 8-bit counters starting at
    ``(0, 0)``; actions increment x or y. The full space is 256*256 =
    65,536 unique states (pinned by the reference, bfs.rs:443).
    """

    a: int
    b: int
    c: int

    def init_states(self):
        return [(0, 0)]

    def actions(self, state):
        return ["IncX", "IncY"]

    def next_state(self, state, action):
        x, y = state
        if action == "IncX":
            return ((x + 1) % 256, y)
        if action == "IncY":
            return (x, (y + 1) % 256)
        return None

    def properties(self):
        return [
            Property.sometimes(
                "solvable",
                lambda m, s: (m.a * s[0] + m.b * s[1]) % 256 == m.c % 256,
            )
        ]


class BinaryClock(Model):
    """Two-state clock: ticks alternate 0/1 (test_util.rs:4-47)."""

    def init_states(self):
        return [0, 1]

    def actions(self, state):
        return ["Tick"]

    def next_state(self, state, action):
        return 1 - state

    def properties(self):
        return [
            Property.always("in bounds", lambda m, s: s in (0, 1)),
            Property.sometimes("can be zero", lambda m, s: s == 0),
        ]


class DGraph(Model):
    """An arbitrary digraph, the eventually-semantics fixture
    (test_util.rs:50-116).

    Build with ``DGraph.with_path([1, 2, 3])`` etc.; attach properties
    with ``.property(...)``.
    """

    def __init__(self):
        self._inits: list[int] = []
        self._edges: dict[int, list[int]] = {}
        self._props: list[Property] = []

    @staticmethod
    def with_path(path: Sequence[int]) -> "DGraph":
        return DGraph().path(path)

    def path(self, path: Sequence[int]) -> "DGraph":
        if not path:
            return self
        if path[0] not in self._inits:
            self._inits.append(path[0])
        for a, b in zip(path, path[1:]):
            succs = self._edges.setdefault(a, [])
            if b not in succs:
                succs.append(b)
        return self

    def node(self, n: int) -> "DGraph":
        if n not in self._inits:
            self._inits.append(n)
        return self

    def property(self, prop: Property) -> "DGraph":
        self._props.append(prop)
        return self

    def init_states(self):
        return list(self._inits)

    def actions(self, state):
        return list(self._edges.get(state, []))

    def next_state(self, state, action):
        return action if action in self._edges.get(state, []) else None

    def properties(self):
        return list(self._props)


class PanickerError(RuntimeError):
    pass


class Panicker(Model):
    """Raises while expanding state 1 — error-propagation fixture
    (test_util.rs:195-228)."""

    def init_states(self):
        return [0]

    def actions(self, state):
        return ["Step"]

    def next_state(self, state, action):
        if state == 1:
            raise PanickerError("boom")
        return state + 1

    def properties(self):
        return [Property.always("under 10", lambda m, s: s < 10)]
