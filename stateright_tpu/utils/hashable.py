"""Stably-hashable immutable set / map collections.

Counterparts of ``HashableHashSet`` / ``HashableHashMap``
(stateright src/util.rs:64-65, 137-159, 349-372): collections whose
digest is insertion-order independent, computed by sorting element
digests before folding — so two states holding the same multimap of
messages fingerprint identically regardless of construction order.
Python dict/set are unhashable and mutable; these wrappers are the
state-safe versions used throughout the actor layer (e.g. network
message collections, src/actor/network.rs:52-55).

Ordering (``__lt__``) is defined on the digest, like the reference's
``Ord`` impl (util.rs:167-177) — arbitrary but total and stable, which
is what symmetry-reduction sorting needs.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, Mapping, Tuple

from ..fingerprint import stable_hash


class HashableSet:
    """Immutable set with a stable, order-independent digest."""

    __slots__ = ("_items", "_digest")

    def __init__(self, items: Iterable[Any] = ()):
        self._items = frozenset(items)
        self._digest: int | None = None

    def _stable_hash_(self) -> int:
        if self._digest is None:
            self._digest = stable_hash(self._items)
        return self._digest

    def add(self, item: Any) -> "HashableSet":
        if item in self._items:
            return self
        return HashableSet(self._items | {item})

    def remove(self, item: Any) -> "HashableSet":
        if item not in self._items:
            return self
        return HashableSet(self._items - {item})

    def __contains__(self, item: Any) -> bool:
        return item in self._items

    def __iter__(self) -> Iterator[Any]:
        return iter(self._items)

    def __len__(self) -> int:
        return len(self._items)

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, HashableSet):
            return self._items == other._items
        return NotImplemented

    def __lt__(self, other: "HashableSet") -> bool:
        return self._stable_hash_() < other._stable_hash_()

    def __hash__(self) -> int:
        return self._stable_hash_()

    def __repr__(self) -> str:
        return "{" + ", ".join(sorted(repr(i) for i in self._items)) + "}"


class HashableMap:
    """Immutable map with a stable, order-independent digest."""

    __slots__ = ("_d", "_digest")

    def __init__(self, items: Mapping | Iterable[Tuple[Any, Any]] = ()):
        self._d = dict(items)
        self._digest: int | None = None

    def _stable_hash_(self) -> int:
        if self._digest is None:
            self._digest = stable_hash(self._d)
        return self._digest

    def set(self, key: Any, value: Any) -> "HashableMap":
        if key in self._d and self._d[key] == value:
            return self
        d = dict(self._d)
        d[key] = value
        return HashableMap(d)

    def remove(self, key: Any) -> "HashableMap":
        if key not in self._d:
            return self
        d = dict(self._d)
        del d[key]
        return HashableMap(d)

    def get(self, key: Any, default: Any = None) -> Any:
        return self._d.get(key, default)

    def items(self):
        return self._d.items()

    def keys(self):
        return self._d.keys()

    def values(self):
        return self._d.values()

    def __getitem__(self, key: Any) -> Any:
        return self._d[key]

    def __contains__(self, key: Any) -> bool:
        return key in self._d

    def __iter__(self) -> Iterator[Any]:
        return iter(self._d)

    def __len__(self) -> int:
        return len(self._d)

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, HashableMap):
            return self._d == other._d
        return NotImplemented

    def __lt__(self, other: "HashableMap") -> bool:
        return self._stable_hash_() < other._stable_hash_()

    def __hash__(self) -> int:
        return self._stable_hash_()

    def __repr__(self) -> str:
        inner = ", ".join(
            f"{k!r}: {v!r}" for k, v in sorted(
                self._d.items(), key=lambda kv: repr(kv[0])
            )
        )
        return "{" + inner + "}"
