"""Dense natural-number-keyed map.

Counterpart of ``DenseNatMap<K, V>`` (stateright
src/util/densenatmap.rs): a type-safe vector keyed by values that
convert to ``int`` (actor ``Id``s in practice) with dense keys —
inserting past the end leaves no gaps (densenatmap.rs:98-113 panics on
gap insert; we raise). Immutable: ``set`` returns a new map.
"""

from __future__ import annotations

from typing import Any, Generic, Iterable, Iterator, Tuple, TypeVar

V = TypeVar("V")


class DenseNatMap(Generic[V]):
    __slots__ = ("_values",)

    def __init__(self, values: Iterable[V] = ()):
        self._values: tuple = tuple(values)

    @staticmethod
    def from_iter(values: Iterable[V]) -> "DenseNatMap[V]":
        return DenseNatMap(values)

    def set(self, key: Any, value: V) -> "DenseNatMap[V]":
        i = int(key)
        if i == len(self._values):
            return DenseNatMap(self._values + (value,))
        if 0 <= i < len(self._values):
            return DenseNatMap(
                self._values[:i] + (value,) + self._values[i + 1:]
            )
        raise IndexError(
            f"gap insert at key {i} (len={len(self._values)}); "
            "DenseNatMap keys must stay dense"
        )

    def __getitem__(self, key: Any) -> V:
        return self._values[int(key)]

    def get(self, key: Any, default: V | None = None) -> V | None:
        i = int(key)
        if 0 <= i < len(self._values):
            return self._values[i]
        return default

    def items(self) -> Iterator[Tuple[int, V]]:
        return enumerate(self._values)

    def values(self) -> tuple:
        return self._values

    def __iter__(self) -> Iterator[V]:
        return iter(self._values)

    def __len__(self) -> int:
        return len(self._values)

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, DenseNatMap):
            return self._values == other._values
        return NotImplemented

    def __hash__(self) -> int:
        return hash(self._values)

    def __repr__(self) -> str:
        return f"DenseNatMap({list(self._values)!r})"
