"""Vector clocks for causal ordering.

Counterpart of stateright src/util/vector_clock.rs: a grow-on-demand
vector of counters with ``merge_max`` / ``incremented``, a causal
partial order (``partial_cmp`` returning None for concurrent clocks,
vector_clock.rs:84-107), and a digest that ignores trailing zeros
(vector_clock.rs:53-63) so ``[1, 0]`` and ``[1]`` are the same clock.
Immutable: updates return new clocks.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

from ..fingerprint import stable_hash


def _trimmed(values: Iterable[int]) -> tuple:
    vals = list(values)
    while vals and vals[-1] == 0:
        vals.pop()
    return tuple(vals)


class VectorClock:
    __slots__ = ("_values",)

    def __init__(self, values: Iterable[int] = ()):
        self._values = _trimmed(values)

    def get(self, index: int) -> int:
        return self._values[index] if index < len(self._values) else 0

    def incremented(self, index: int) -> "VectorClock":
        """Return a clock with component ``index`` bumped
        (vector_clock.rs:20-39)."""
        n = max(len(self._values), index + 1)
        vals = [self.get(i) for i in range(n)]
        vals[index] += 1
        return VectorClock(vals)

    def merge_max(self, other: "VectorClock") -> "VectorClock":
        """Component-wise max — the receive-side merge."""
        n = max(len(self._values), len(other._values))
        return VectorClock(
            max(self.get(i), other.get(i)) for i in range(n)
        )

    def partial_cmp(self, other: "VectorClock") -> Optional[int]:
        """-1 if self < other, 0 if equal, 1 if self > other, None if
        concurrent (vector_clock.rs:84-107)."""
        n = max(len(self._values), len(other._values))
        lt = gt = False
        for i in range(n):
            a, b = self.get(i), other.get(i)
            if a < b:
                lt = True
            elif a > b:
                gt = True
        if lt and gt:
            return None
        if lt:
            return -1
        if gt:
            return 1
        return 0

    def __le__(self, other: "VectorClock") -> bool:
        cmp = self.partial_cmp(other)
        return cmp is not None and cmp <= 0

    def __lt__(self, other: "VectorClock") -> bool:
        return self.partial_cmp(other) == -1

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, VectorClock):
            return self._values == other._values
        return NotImplemented

    def _stable_hash_(self) -> int:
        return stable_hash(self._values)

    def __hash__(self) -> int:
        return hash(self._values)

    def __repr__(self) -> str:
        return f"VectorClock({list(self._values)!r})"
