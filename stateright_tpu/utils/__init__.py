"""Utility data structures embedded in model states.

TPU-native counterparts of the reference's L0 utilities
(stateright src/util.rs, src/util/{densenatmap,vector_clock}.rs).
All collections here are *immutable* (updates return new values): model
states must be safely shareable between frontier entries, and the
fingerprint of a state must never change after it is computed.
"""

from .hashable import HashableMap, HashableSet
from .densenatmap import DenseNatMap
from .vector_clock import VectorClock

__all__ = ["HashableMap", "HashableSet", "DenseNatMap", "VectorClock"]
