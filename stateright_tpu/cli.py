"""Per-example CLI entry points.

The reference ships each example as a binary with ``check`` /
``check-sym`` / ``explore`` / ``spawn`` subcommands (e.g.
examples/paxos.rs:352-465, examples/2pc.rs:172-251); here one module
dispatches the same surface for every bundled workload:

    python -m stateright_tpu 2pc check 3
    python -m stateright_tpu 2pc check-sym 5
    python -m stateright_tpu 2pc check-tpu 6          (wave engine)
    python -m stateright_tpu 2pc-actors check-tpu 5   (compiled encoding)
    python -m stateright_tpu paxos check 2 [network]
    python -m stateright_tpu paxos-compiled check-tpu (compiled encoding)
    python -m stateright_tpu paxos check-tpu 4 --trace    (run telemetry)
    python -m stateright_tpu paxos explore 2 localhost:3000
    python -m stateright_tpu paxos spawn

``check`` engines mirror the reference's per-example choices (DFS
everywhere except interaction-style BFS cases); ``check-tpu`` — the
addition this framework exists for — runs the same workload on the
accelerator wave engine. Output goes through ``WriteReporter`` so the
report shape (``Done. states=… unique=… …``) matches report.rs:60-98.

``--trace`` (anywhere on the line) records run telemetry
(stateright_tpu/telemetry.py): per-wave events from the engine's
device wave log, host-phase spans, and the chunk dispatch/fetch wall
split, exported as auto-numbered ``TRACE_r*.jsonl`` +
``TRACE_r*.trace.json`` (Chrome trace) in the repo root.
``--trace=deep`` adds per-wave syncs for real per-wave wall times.
Diff two trace artifacts with ``tools/trace_diff.py``.
"""

from __future__ import annotations

import sys
import threading

from .actor.network import Network
from .report import WriteReporter


def _opt(args: list[str], index: int, default, parse=int):
    if len(args) > index:
        return parse(args[index])
    return default


def _network(args: list[str], index: int) -> Network:
    name = _opt(args, index, None, parse=str)
    if name is None:
        return Network.new_unordered_nonduplicating()
    return Network.from_name(name)


class _ThreadLocalRuntime:
    """Dict-like, PER-THREAD runtime-flag store: ``main()`` is
    re-entered in-process (tests, embedders) and — since the resident
    service (stateright_tpu/serve.py) runs sessions on concurrent
    HTTP threads — a process-global dict would let one invocation's
    reset silently wipe another thread's popped flags between its pop
    and its ``_apply_runtime``. Each thread sees its own copy,
    initialized to the defaults; supports exactly the dict surface
    the flag plumbing uses (``[]`` get/set, ``update(**kw)``)."""

    def __init__(self, **defaults):
        self._defaults = dict(defaults)
        self._tls = threading.local()

    def _cfg(self) -> dict:
        cfg = getattr(self._tls, "cfg", None)
        if cfg is None:
            cfg = dict(self._defaults)
            self._tls.cfg = cfg
        return cfg

    def __getitem__(self, key):
        return self._cfg()[key]

    def __setitem__(self, key, value):
        self._cfg()[key] = value

    def update(self, **kw) -> None:
        self._cfg().update(kw)


#: runtime flags popped by main() and applied at the one reporting
#: seam every check lane shares (_report): checkpoint/resume
#: (stateright_tpu/checkpoint.py) and the waves-per-sync override
#: (sets the chunk cadence — and therefore the checkpoint cadence —
#: without a per-lane knob). Thread-scoped — see _ThreadLocalRuntime.
_RUNTIME = _ThreadLocalRuntime(
    checkpoint_every=None, checkpoint_path=None, resume=False,
    resume_any_sha=False, waves_per_sync=None, tier_hot_rows=None,
    degrade_on_fault=False, watchdog=None, straggler_factor=None,
    symmetry=False, ample_set=False, unsound_ok=False,
)


def _maybe_symmetry(builder):
    """``--symmetry``: arm the builder's symmetry reduction BEFORE the
    spawn (the capability refusal fires in the engine constructor,
    checkers/common.symmetry_refusal, and the soundness-certificate
    gate right after it, analysis/soundness.gate_symmetry) — device
    engines canonicalize candidate fingerprints through the encoding's
    DeviceRewriteSpec (ops/canonical.py). ``--unsound-ok`` is armed
    here too: the waiver must reach the builder before the spawn-time
    gate fires."""
    if _RUNTIME["unsound_ok"]:
        builder = builder.unsound_ok()
    if _RUNTIME["symmetry"]:
        return builder.symmetry()
    return builder


def _apply_runtime(checker) -> None:
    """Apply the popped runtime flags to a freshly-spawned checker
    (before its first join). Device engines only: the flags configure
    the chunk loop, which host checkers don't have."""
    cfg = _RUNTIME
    if cfg["symmetry"]:
        # pre-spawn flag (_maybe_symmetry); by the time the checker
        # reaches this seam the builder must already carry it — a lane
        # that never called _maybe_symmetry would otherwise silently
        # run unreduced
        builder = getattr(checker, "builder", None)
        if builder is not None and builder._symmetry is None:
            raise SystemExit(
                "--symmetry: this lane does not arm the symmetry "
                "reduction (supported: 2pc check-tpu — the device "
                "canonicalization lane — and the host check-sym lanes)"
            )
    if cfg["ample_set"]:
        if not hasattr(checker, "ample_set"):
            raise SystemExit(
                "--ample-set needs a sort-merge check-tpu lane (the "
                "filter ANDs the encoding's ample mask into the "
                "sparse enabled bitmap, checkers/tpu_sortmerge.py)"
            )
        checker.ample_set = True
    if cfg["unsound_ok"] and hasattr(checker, "unsound_ok"):
        # the ample certificate gate fires at program-build time
        # (_resolve_ample_words), after this seam — the waiver must
        # land on the checker, not just the builder
        checker.unsound_ok = True
    if not (cfg["checkpoint_every"] or cfg["resume"]
            or cfg["waves_per_sync"] or cfg["tier_hot_rows"]
            or cfg["degrade_on_fault"] or cfg["watchdog"]
            or cfg["straggler_factor"]):
        return
    if not hasattr(checker, "_run_attempt"):
        raise SystemExit(
            "--checkpoint-every/--resume/--waves-per-sync/"
            "--tier-hot-rows/--degrade-on-fault/--watchdog/"
            "--straggler-factor need a device engine: use a "
            "check-tpu lane"
        )
    if cfg["degrade_on_fault"]:
        # the degrade path needs a snapshot to re-shard; it engages
        # only on multi-shard engines (single-chip has nothing to
        # drop), but configuring it there is harmless — the policy
        # gate is _can_degrade_shards
        checker.degrade_on_fault = True
    if cfg["watchdog"]:
        checker.watchdog_factor = float(cfg["watchdog"])
    if cfg["straggler_factor"]:
        checker.straggler_factor = float(cfg["straggler_factor"])
    if cfg["tier_hot_rows"]:
        if not hasattr(checker, "tier_hot_rows"):
            raise SystemExit(
                "--tier-hot-rows needs a sort-merge engine (the "
                "tiered visited set lives in the sorted-prefix "
                "family, stateright_tpu/tier.py)"
            )
        checker.tier_hot_rows = cfg["tier_hot_rows"]
        checker._tier_hot_ceiling = None
    if cfg["waves_per_sync"]:
        checker.waves_per_sync = cfg["waves_per_sync"]
    path = cfg["checkpoint_path"] or "stateright_tpu.ckpt"
    if cfg["checkpoint_every"]:
        checker.checkpoint_every = cfg["checkpoint_every"]
        checker.checkpoint_path = path
    if cfg["resume"]:
        manifest = checker.resume_from(
            path, allow_sha_mismatch=cfg["resume_any_sha"]
        )
        print(
            f"resuming from {path}: wave {manifest['wave']}, depth "
            f"{manifest['depth']}, {manifest['unique']:,} unique "
            f"states (snapshot S={manifest['n_shards']})"
        )


#: thread-scoped session hook (the resident service,
#: stateright_tpu/serve.py): the service installs a callback here
#: around each session's handler call, and ``_report`` runs it on the
#: freshly-spawned checker BEFORE the first join — admission, warm
#: start, the FIFO device gate, retention arming. Thread-local so
#: concurrent service sessions (and a plain in-process ``main()``
#: embedder on another thread) never see each other's hook. This is
#: also why a second same-config check in one process provably hits
#: the ``in_process`` compile-ledger tier: every lane funnels its
#: checker through THIS one seam, whose engines share the process
#: program cache (tests/test_serve.py pins the tier).
_SESSION_HOOK = threading.local()


def _report(checker, out=None) -> None:
    """The one reporting path every check lane shares: the reference-
    format ``Reporter`` (report.rs:60-98) — no lane formats privately
    (tests/test_report.py pins the format through this seam). Also
    the seam the popped runtime flags (checkpoint/resume) land on —
    and the seam the resident service intercepts (``_SESSION_HOOK``):
    every check lane passes its checker through here before the first
    join."""
    _apply_runtime(checker)
    hook = getattr(_SESSION_HOOK, "hook", None)
    if hook is not None:
        hook(checker)
    checker.report(WriteReporter(out if out is not None else sys.stdout))


def _explore(builder, args: list[str], index: int) -> None:
    address = _opt(args, index, "localhost:3000", parse=str)
    builder.serve(address)


# -- workloads -----------------------------------------------------------


def _2pc(sub: str, args: list[str]) -> None:
    from .models.two_phase_commit import TwoPhaseSys

    rm_count = _opt(args, 0, 2)
    sys_model = TwoPhaseSys(rm_count=rm_count)
    if sub == "check":
        print(f"Checking two phase commit with {rm_count} resource managers.")
        _report(sys_model.checker().spawn_dfs())
    elif sub == "check-sym":
        print(
            f"Checking two phase commit with {rm_count} resource managers "
            "using symmetry reduction."
        )
        _report(sys_model.checker().symmetry().spawn_dfs())
    elif sub == "check-tpu":
        print(
            f"Checking two phase commit with {rm_count} resource managers "
            "on the TPU wave engine."
        )
        # The 2pc space grows ~2.53 bits/RM (288 @ 3 → 296,448 @ 7).
        # The sort-merge visited array has no load-factor pressure, so
        # a snug capacity works; this is the engine bench.py records
        # (the hash-table engine measured ~10x slower on chip, PERF.md).
        import math

        capacity = 1 << max(10, math.ceil(2.6 * rm_count + 1.5))
        _report(
            _maybe_symmetry(sys_model.checker()).spawn_tpu_sortmerge(
                capacity=capacity,
                frontier_capacity=max(256, capacity // 4),
                cand_capacity="auto",
            )
        )
    elif sub == "explore":
        address = _opt(args, 1, "localhost:3000", parse=str)
        print(
            f"Exploring state space for two phase commit with {rm_count} "
            f"resource managers on {address}."
        )
        sys_model.checker().serve(address)
    else:
        _usage("2pc")


def _paxos(sub: str, args: list[str]) -> None:
    from .models.paxos import PaxosModelCfg, paxos_model

    client_count = _opt(args, 0, 2)
    cfg = PaxosModelCfg(client_count=client_count, server_count=3)
    if sub == "check":
        network = _network(args, 1)
        print(f"Model checking Single Decree Paxos with {client_count} clients.")
        _report(paxos_model(cfg, network).checker().spawn_dfs())
    elif sub == "check-tpu":
        print(
            f"Model checking Single Decree Paxos with {client_count} "
            "clients on the TPU wave engine."
        )
        # STRUCTURAL sizes from the one shared table; per-wave budgets
        # auto-size from measured peaks (cand_capacity="auto") — the
        # round-5 TUNED_ENGINE_CAPS budget table is retired (VERDICT
        # r5 item 6).
        from .models.paxos_tpu import STRUCTURAL_SIZES as sizes

        if client_count not in sizes:
            raise SystemExit(
                f"paxos check-tpu supports 1-5 clients (got "
                f"{client_count}): the TPU encoding's client-lane "
                "packing caps at 5 (models/paxos_tpu.py)"
            )
        _report(
            paxos_model(cfg)
            .checker()
            .spawn_tpu_sortmerge(
                track_paths=client_count <= 2,
                cand_capacity="auto",
                **sizes[client_count],
            )
        )
    elif sub == "explore":
        address = _opt(args, 1, "localhost:3000", parse=str)
        network = _network(args, 2)
        print(
            f"Exploring state space for Single Decree Paxos with "
            f"{client_count} clients on {address}."
        )
        paxos_model(cfg, network).checker().serve(address)
    elif sub == "spawn":
        from .actor.spawn import spawn_paxos_cluster

        spawn_paxos_cluster()
    else:
        _usage("paxos")


def _2pc_actors(sub: str, args: list[str]) -> None:
    """The COMPILED 2pc family (round 23): the count-comparable
    system actor model (models/two_phase_commit_actors.py
    two_phase_sys_actor_model — host-parity pinned at the TwoPhaseSys
    counts, 8,832 @ rm=5) through the generic actor→encoding
    compiler's optimized codegen. Routing through ``_report`` gives
    the compiled path ``--trace`` / ``--checkpoint-every`` / resume
    for free, same as every hand lane."""
    from .models.two_phase_commit_actors import (
        two_phase_sys_actor_model,
        two_phase_sys_compiled_encoded,
    )

    rm_count = _opt(args, 0, 2)
    model = two_phase_sys_actor_model(rm_count)
    if sub == "check":
        print(
            f"Checking two phase commit (compiled actor model) with "
            f"{rm_count} resource managers."
        )
        _report(model.checker().spawn_dfs())
    elif sub == "check-tpu":
        print(
            f"Checking two phase commit (compiled actor model) with "
            f"{rm_count} resource managers on the TPU wave engine."
        )
        # Same pinned counts as the hand `2pc` lanes (~2.53 bits/RM),
        # same snug-capacity sizing; the encoding comes from the
        # compiler, not models/two_phase_commit_tpu.py.
        import math

        capacity = 1 << max(10, math.ceil(2.6 * rm_count + 1.5))
        _report(
            model.checker().spawn_tpu_sortmerge(
                encoded=two_phase_sys_compiled_encoded(rm_count),
                capacity=capacity,
                frontier_capacity=max(256, capacity // 4),
                cand_capacity="auto",
            )
        )
    elif sub == "explore":
        address = _opt(args, 1, "localhost:3000", parse=str)
        print(
            f"Exploring state space for two phase commit (compiled "
            f"actor model) with {rm_count} resource managers on "
            f"{address}."
        )
        model.checker().serve(address)
    else:
        _usage("2pc-actors")


def _paxos_compiled(sub: str, args: list[str]) -> None:
    """Compiled paxos (round 23): the actor paxos model through the
    compiler in reachable mode — the compile pays ONE host
    exploration of the space to harvest bounds, so this lane caps at
    2 clients (the bench's production shape, 16,668 states)."""
    from .models.paxos import (
        PaxosModelCfg,
        paxos_compiled_encoded,
        paxos_model,
    )

    client_count = _opt(args, 0, 2)
    cfg = PaxosModelCfg(client_count=client_count, server_count=3)
    if sub == "check":
        print(
            f"Model checking Single Decree Paxos (compiled) with "
            f"{client_count} clients."
        )
        _report(paxos_model(cfg).checker().spawn_dfs())
    elif sub == "check-tpu":
        if client_count > 2:
            raise SystemExit(
                f"paxos-compiled check-tpu supports 1-2 clients (got "
                f"{client_count}): reachable-mode compilation "
                "explores the space once on the host to harvest "
                "bounds (models/paxos.py paxos_compiled_encoded), "
                "which is impractical beyond the 16,668-state "
                "2-client config"
            )
        print(
            f"Model checking Single Decree Paxos (compiled) with "
            f"{client_count} clients on the TPU wave engine."
        )
        _report(
            paxos_model(cfg)
            .checker()
            .spawn_tpu_sortmerge(
                encoded=paxos_compiled_encoded(cfg),
                track_paths=client_count <= 2,
                capacity=1 << 15,
                frontier_capacity=1 << 13,
                cand_capacity="auto",
            )
        )
    else:
        _usage("paxos-compiled")


def _increment(sub: str, args: list[str]) -> None:
    from .models.increment import Increment

    thread_count = _opt(args, 0, 2)
    model = Increment(thread_count=thread_count)
    if sub == "check":
        print(f"Model checking increment with {thread_count} threads.")
        _report(model.checker().spawn_dfs())
    elif sub == "check-tpu":
        print(
            f"Model checking increment with {thread_count} threads "
            "on the TPU wave engine."
        )
        _report(
            model.checker().spawn_tpu_sortmerge(
                capacity=1 << 12, frontier_capacity=256, cand_capacity=1024
            )
        )
    elif sub == "check-sym":
        print(
            f"Model checking increment with {thread_count} threads "
            "using symmetry reduction."
        )
        _report(model.checker().symmetry().spawn_dfs())
    elif sub == "explore":
        _explore(model.checker(), args, 1)
    else:
        _usage("increment")


def _increment_lock(sub: str, args: list[str]) -> None:
    from .models.increment import IncrementLock

    thread_count = _opt(args, 0, 3)
    model = IncrementLock(thread_count=thread_count)
    if sub == "check":
        print(f"Model checking increment_lock with {thread_count} threads.")
        _report(model.checker().spawn_dfs())
    elif sub == "check-tpu":
        print(
            f"Model checking increment_lock with {thread_count} threads "
            "on the TPU wave engine."
        )
        _report(
            model.checker().spawn_tpu_sortmerge(
                capacity=1 << 12, frontier_capacity=256, cand_capacity=1024
            )
        )
    elif sub == "check-sym":
        print(
            f"Model checking increment_lock with {thread_count} threads "
            "using symmetry reduction."
        )
        _report(model.checker().symmetry().spawn_dfs())
    elif sub == "explore":
        _explore(model.checker(), args, 1)
    else:
        _usage("increment-lock")


def _single_copy(sub: str, args: list[str]) -> None:
    from .models.single_copy_register import (
        SingleCopyRegisterCfg,
        single_copy_register_model,
    )

    client_count = _opt(args, 0, 2)
    cfg = SingleCopyRegisterCfg(client_count=client_count)
    if sub == "check":
        network = _network(args, 1)
        print(
            f"Model checking a single-copy register with {client_count} "
            "clients."
        )
        _report(single_copy_register_model(cfg, network).checker().spawn_dfs())
    elif sub == "check-tpu":
        print(
            f"Model checking a single-copy register with {client_count} "
            "clients on the TPU wave engine."
        )
        _report(
            single_copy_register_model(cfg)
            .checker()
            .spawn_tpu_sortmerge(
                capacity=256, frontier_capacity=64, cand_capacity=256
            )
        )
    elif sub == "explore":
        address = _opt(args, 1, "localhost:3000", parse=str)
        network = _network(args, 2)
        print(
            f"Exploring state space for a single-copy register with "
            f"{client_count} clients on {address}."
        )
        single_copy_register_model(cfg, network).checker().serve(address)
    elif sub == "spawn":
        from .actor.spawn import spawn_single_copy_cluster

        spawn_single_copy_cluster()
    else:
        _usage("single-copy-register")


def _linearizable(sub: str, args: list[str]) -> None:
    from .models.linearizable_register import AbdModelCfg, abd_model

    client_count = _opt(args, 0, 2)
    cfg = AbdModelCfg(client_count=client_count)
    if sub == "check":
        network = _network(args, 1)
        print(
            f"Model checking a linearizable register with {client_count} "
            "clients."
        )
        _report(abd_model(cfg, network).checker().spawn_dfs())
    elif sub == "check-tpu":
        network = _network(args, 1)
        print(
            f"Model checking a linearizable register with {client_count} "
            "clients on the TPU wave engine (compiled actor encoding)."
        )
        _report(
            abd_model(cfg, network)
            .checker()
            .spawn_tpu_sortmerge(
                capacity=1 << (9 + 2 * client_count),
                frontier_capacity=1 << (7 + client_count),
                cand_capacity=1 << (9 + client_count),
            )
        )
    elif sub == "explore":
        address = _opt(args, 1, "localhost:3000", parse=str)
        network = _network(args, 2)
        print(
            f"Exploring state space for a linearizable register with "
            f"{client_count} clients on {address}."
        )
        abd_model(cfg, network).checker().serve(address)
    elif sub == "spawn":
        from .actor.spawn import spawn_abd_cluster

        spawn_abd_cluster()
    else:
        _usage("linearizable-register")


def _timers(sub: str, args: list[str]) -> None:
    from .models.timers import PingerModelCfg, pinger_model

    server_count = _opt(args, 0, 3)
    cfg = PingerModelCfg(server_count=server_count)
    if sub == "check":
        network = _network(args, 1)
        print("Model checking Pingers")
        # The pinger space is unbounded (timers.rs runs it the same
        # way); interrupt or pass a depth bound via `explore`.
        _report(pinger_model(cfg, network).checker().spawn_dfs())
    elif sub == "explore":
        address = _opt(args, 1, "localhost:3000", parse=str)
        network = _network(args, 2)
        print(f"Exploring state space for Pingers on {address}.")
        pinger_model(cfg, network).checker().serve(address)
    else:
        _usage("timers")


def _interaction(sub: str, args: list[str]) -> None:
    from .models.interaction import interaction_model

    if sub == "check":
        # interaction.rs:44 bounds the loosely-bounded space at depth
        # 30; an optional DEPTH argument trades coverage for time (the
        # reference explores this space with a Rust thread pool).
        depth = _opt(args, 0, 30)
        checker = (
            interaction_model().checker().target_max_depth(depth).spawn_bfs()
        )
        _report(checker)
        checker.assert_properties()
    elif sub == "explore":
        address = _opt(args, 0, "localhost:3000", parse=str)
        print(f"Exploring the interaction model on {address}.")
        interaction_model().checker().target_max_depth(30).serve(address)
    else:
        _usage("interaction")


def _panic(sub: str, args: list[str]) -> None:
    """Counterpart of examples/panic.rs: a model whose action
    enumeration raises mid-search. The reference uses it to verify a
    worker-thread panic propagates out of ``join()`` instead of
    hanging the checker; here the search runs in-process and the
    checker must surface the error to the caller unchanged."""
    from .model import Model, Property

    class Adder(Model):
        def init_states(self):
            return [0]

        def actions(self, state):
            if state >= 5000:
                raise RuntimeError(
                    "panic! (the examples/panic.rs trigger: action "
                    f"enumeration raised at state {state})"
                )
            return [1, 2, 3, 4, 5]

        def next_state(self, state, action):
            return state + action

        def properties(self):
            return [Property.always("true", lambda m, s: True)]

    if sub == "check":
        print(
            "Checking the panicking adder (examples/panic.rs): the "
            "search must fail loudly, not hang."
        )
        try:
            Adder().checker().spawn_dfs().join()
        except RuntimeError as e:
            if "panic!" not in str(e):
                raise  # an unrelated checker failure, not the trigger
            print(f"Checker propagated the panic: {e}")
            return
        raise SystemExit("ERROR: the panic did not propagate")
    else:
        _usage("panic")


def _register(sub: str, args: list[str]) -> None:
    from .models.nclient_register import NClientRegSys

    n_clients = _opt(args, 0, 3)
    sys_model = NClientRegSys(n_clients=n_clients)
    if sub == "check":
        print(
            f"Checking the write-once register with {n_clients} clients."
        )
        _report(sys_model.checker().spawn_dfs())
    elif sub == "check-sym":
        print(
            f"Checking the write-once register with {n_clients} clients "
            "using symmetry reduction."
        )
        _report(sys_model.checker().symmetry().spawn_dfs())
    elif sub == "check-tpu":
        print(
            f"Checking the write-once register with {n_clients} clients "
            "on the TPU wave engine."
        )
        # raw space is 1 + 2n*3^(n-1) states (models/nclient_register)
        # — tiny; snug pow-2 capacity over the closed form
        raw = 1 + 2 * n_clients * 3 ** max(0, n_clients - 1)
        capacity = max(1 << 10, 1 << (raw - 1).bit_length())
        _report(
            _maybe_symmetry(sys_model.checker()).spawn_tpu_sortmerge(
                capacity=capacity,
                frontier_capacity=max(256, capacity // 4),
                cand_capacity="auto",
            )
        )
    elif sub == "explore":
        address = _opt(args, 1, "localhost:3000", parse=str)
        print(
            f"Exploring state space for the write-once register with "
            f"{n_clients} clients on {address}."
        )
        sys_model.checker().serve(address)
    else:
        _usage("register")


_MODELS = {
    "2pc": (_2pc, ["check", "check-sym", "check-tpu", "explore"]),
    "2pc-actors": (_2pc_actors, ["check", "check-tpu", "explore"]),
    "register": (_register, ["check", "check-sym", "check-tpu", "explore"]),
    "paxos": (_paxos, ["check", "check-tpu", "explore", "spawn"]),
    "paxos-compiled": (_paxos_compiled, ["check", "check-tpu"]),
    "increment": (_increment, ["check", "check-sym", "check-tpu", "explore"]),
    "increment-lock": (_increment_lock, ["check", "check-sym", "check-tpu", "explore"]),
    "single-copy-register": (_single_copy, ["check", "check-tpu", "explore", "spawn"]),
    "linearizable-register": (_linearizable, ["check", "check-tpu", "explore", "spawn"]),
    "timers": (_timers, ["check", "explore"]),
    "interaction": (_interaction, ["check", "explore"]),
    "panic": (_panic, ["check"]),
}


def _usage(model: str | None = None) -> None:
    print("USAGE:")
    if model is None:
        for name, (_, subs) in _MODELS.items():
            print(f"  python -m stateright_tpu {name} {{{'|'.join(subs)}}} ...")
    else:
        _, subs = _MODELS[model]
        for sub in subs:
            extra = {
                "check": "[COUNT] [NETWORK]",
                "check-sym": "[COUNT]",
                "check-tpu": "[COUNT]",
                "explore": "[COUNT] [ADDRESS] [NETWORK]",
                "spawn": "",
            }[sub]
            if model == "panic":
                extra = ""  # fixed harness: no count, no network
            print(f"  python -m stateright_tpu {model} {sub} {extra}")
    if model is None:
        print(
            "  python -m stateright_tpu serve [HOST:PORT] "
            "[--explore=MODEL[,COUNT]] [--program-budget-bytes=N] "
            "[--device-budget-bytes=N] [--no-warm-start] "
            "[--batch-sessions[=N]] [--batch-window-sec=S] "
            "[--snapshot-budget-bytes=N] [--metrics-interval=N "
            "[--metrics-path=FILE]]"
        )
    print(f"NETWORK: {' | '.join(Network.names())}")
    print(
        "FLAGS: --trace[=deep] on any check lane writes TRACE_r*.jsonl"
        " + TRACE_r*.trace.json run telemetry (tools/trace_diff.py "
        "compares two)"
    )
    print(
        "       --checkpoint-every=N|auto [--checkpoint-path=P] on "
        "check-tpu lanes snapshots the chunk carry every N chunks "
        "(atomic; supervised fault retry; 'auto' picks the cadence "
        "from the measured snapshot-vs-chunk walls, <=5% overhead); "
        "--resume restores from the snapshot — elastically, onto a "
        "different shard count on the sort-merge engines "
        "(--resume-any-sha skips the git-SHA staleness refusal; "
        "--waves-per-sync=N sets the chunk cadence)"
    )
    print(
        "       --tier-hot-rows=N|auto on sort-merge check-tpu "
        "lanes caps the device-resident visited HOT tier at N rows "
        "and spills the rest to host-DRAM cold runs "
        "(stateright_tpu/tier.py; 'auto' = the memplan capacity "
        "projection decides the split) — reachability bounded by "
        "host memory, not HBM"
    )
    print(
        "       --degrade-on-fault on check-tpu lanes lets the "
        "supervisor DROP a persistently-faulting shard and re-shard "
        "the last snapshot onto the survivors (degrade-and-continue,"
        " checkpoint.FailurePolicy); --watchdog[=factor] arms the "
        "hung-dispatch watchdog (deadline = clamp(factor x rolling "
        "max chunk wall), default 8 — a breach emits "
        "watchdog_timeout + recovers from the snapshot or refuses "
        "loudly); --straggler-factor=F emits shard_health events "
        "when a shard's wave work exceeds F x the shard median "
        "(traced mesh runs; sustained stragglers feed the failure "
        "classifier)"
    )
    print(
        "       --symmetry on 2pc/register check-tpu runs the device "
        "symmetry reduction (canonical-form fingerprints before "
        "dedup, ops/canonical.py; 2pc rm=5: 8,832 -> 314 states); "
        "--ample-set on sort-merge check-tpu lanes ANDs the "
        "encoding's partial-order ample mask into the sparse "
        "enabled-bits pass (fewer interleavings, same verdicts). "
        "Both consult the reduction soundness certificate "
        "(analysis/soundness.py): uncertifiable specs refuse at "
        "spawn with the failed obligation; --unsound-ok waives the "
        "gate (no soundness guarantee)"
    )
    print(
        "       `analyze soundness [MODEL] [COUNT] [--no-artifact]` "
        "runs the reduction soundness analyzer over the registered "
        "targets (2pc, register) and writes the SOUND_r*.json "
        "certificate the engine gates consult"
    )
    print(
        "       `serve` runs the resident multi-tenant checking "
        "service (stateright_tpu/serve.py): one warm process, a FIFO "
        "device queue, a byte-budget LRU of compiled programs, "
        "fingerprint-stable warm-start re-checks, and an optional "
        "Explorer mount; --connect=HOST:PORT on any check lane ships "
        "it to a running service (counts bit-identical, compile "
        "amortized); --batch-sessions[=N] fuses up to N concurrent "
        "compatible sessions into ONE wave-program dispatch "
        "(stateright_tpu/batch.py — per-session counts/verdicts/paths "
        "stay bit-exact, the dispatch+sync floor is amortized 1/N; "
        "--batch-window-sec=S sets the admission batching window); "
        "--snapshot-budget-bytes=N caps the warm-start snapshot spool "
        "with byte-budget LRU eviction (snapshot_evict events)"
    )
    print(
        "       --metrics-interval=N [--metrics-path=FILE] on any "
        "check lane (and on `serve`) appends one cumulative "
        "metrics_rollup JSONL line every N seconds — the live "
        "metrics plane (stateright_tpu/metrics.py: counters/gauges/"
        "log-bucket histograms, bridge-derived from telemetry) for "
        "headless runs; the serve daemon also answers GET /.metrics "
        "in Prometheus text format, and tools/slo_report.py "
        "exit-code-gates a rollup or live endpoint against a "
        "declarative SLO spec (p50/p99 time-to-verdict, refusal "
        "rate, queue wait, cache-hit rate)"
    )


def _pop_connect_flag(argv: list[str]) -> tuple[str | None, list[str]]:
    """Strip ``--connect=HOST:PORT`` from anywhere in argv: client
    mode — the remaining lane argv ships to a resident checking
    service (stateright_tpu/serve.py) instead of running cold in this
    process. Counts are bit-identical (the service runs the same
    handler, warm); latency skips the per-process compile."""
    addr = None
    rest = []
    for a in argv:
        if a.startswith("--connect="):
            addr = a.split("=", 1)[1]
        elif a == "--connect":
            raise SystemExit(
                "--connect needs an address: --connect=HOST:PORT"
            )
        else:
            rest.append(a)
    return addr, rest


def _pop_trace_flag(argv: list[str]) -> tuple[str | None, list[str]]:
    """Strip ``--trace`` / ``--trace=deep`` from anywhere in argv."""
    level = None
    rest = []
    for a in argv:
        if a == "--trace":
            level = "default"
        elif a.startswith("--trace="):
            level = a.split("=", 1)[1]
        else:
            rest.append(a)
    return level, rest


def _pop_metrics_flags(
    argv: list[str],
) -> tuple[float | None, str | None, list[str]]:
    """Strip ``--metrics-interval=N`` / ``--metrics-path=FILE`` from
    anywhere in argv: the headless metrics export
    (stateright_tpu/metrics.py) — a tracer runs for the lane (even
    without ``--trace``) and every N seconds its events are folded
    through the tracer→metrics bridge into one cumulative
    ``metrics_rollup`` JSONL line (default path
    ``stateright_tpu.metrics.jsonl``), plus a final line at exit.
    TRACE artifacts are still only written when ``--trace`` asked
    for them."""
    interval = None
    path = None
    rest = []
    for a in argv:
        if a.startswith("--metrics-interval="):
            val = a.split("=", 1)[1]
            interval = float(val)
            if interval <= 0:
                raise SystemExit(
                    f"--metrics-interval={val}: must be > 0 seconds"
                )
        elif a == "--metrics-interval":
            raise SystemExit(
                "--metrics-interval needs a cadence: "
                "--metrics-interval=N (seconds)"
            )
        elif a.startswith("--metrics-path="):
            path = a.split("=", 1)[1]
        else:
            rest.append(a)
    if interval is None and path is not None:
        raise SystemExit(
            "--metrics-path requires --metrics-interval=N"
        )
    return interval, path, rest


def _pop_runtime_flags(argv: list[str]) -> list[str]:
    """Strip the checkpoint/resume flags from anywhere in argv into
    :data:`_RUNTIME` (the durability layer,
    stateright_tpu/checkpoint.py): ``--checkpoint-every=N`` (snapshot
    the chunk carry every N chunks + supervised fault retry),
    ``--checkpoint-path=PATH`` (default ``stateright_tpu.ckpt``),
    ``--resume`` (restore from the checkpoint path — elastic: a
    sort-merge snapshot resumes onto a different shard count),
    ``--resume-any-sha`` (skip the git-SHA staleness refusal), and
    ``--waves-per-sync=N`` (chunk cadence override — the knob that
    sets how much progress one snapshot covers)."""
    rest = []
    for a in argv:
        if a.startswith("--checkpoint-every="):
            val = a.split("=", 1)[1]
            # "auto": cadence from the measured snapshot write wall
            # vs chunk wall (checkpoint.auto_cadence, <=5% overhead)
            _RUNTIME["checkpoint_every"] = (
                "auto" if val == "auto" else int(val)
            )
        elif a.startswith("--tier-hot-rows="):
            val = a.split("=", 1)[1]
            # tiered visited set (stateright_tpu/tier.py): hot-tier
            # ceiling in rows, or "auto" for the memplan-projection
            # split. Validated HERE: a 0 would be silently dropped
            # by the apply-time truthiness gate instead of refused.
            if val != "auto" and int(val) < 1:
                raise SystemExit(
                    f"--tier-hot-rows={val}: the hot ceiling must "
                    "be >= 1 row (or 'auto')"
                )
            _RUNTIME["tier_hot_rows"] = (
                "auto" if val == "auto" else int(val)
            )
        elif a.startswith("--checkpoint-path="):
            _RUNTIME["checkpoint_path"] = a.split("=", 1)[1]
        elif a == "--resume":
            _RUNTIME["resume"] = True
        elif a == "--resume-any-sha":
            _RUNTIME["resume"] = True
            _RUNTIME["resume_any_sha"] = True
        elif a.startswith("--waves-per-sync="):
            _RUNTIME["waves_per_sync"] = int(a.split("=", 1)[1])
        elif a == "--degrade-on-fault":
            # degrade-and-continue (checkpoint.FailurePolicy): a
            # fault that persists on one shard drops that shard and
            # re-shards the last snapshot onto the survivors
            _RUNTIME["degrade_on_fault"] = True
        elif a == "--watchdog" or a.startswith("--watchdog="):
            # hung-dispatch watchdog (checkers/tpu.py): deadline =
            # clamp(factor x rolling max chunk wall), default factor 8
            val = a.split("=", 1)[1] if "=" in a else "8"
            f = float(val)
            if f <= 0:
                raise SystemExit(
                    f"--watchdog={val}: the factor must be > 0"
                )
            _RUNTIME["watchdog"] = f
        elif a == "--symmetry":
            # device symmetry reduction (ops/canonical.py): canonical
            # fingerprints before dedup — armed on the builder pre-
            # spawn (_maybe_symmetry); engines that can't honor it
            # refuse loudly at spawn
            _RUNTIME["symmetry"] = True
        elif a == "--ample-set":
            # partial-order-reduction enabled-bits filter: AND the
            # encoding's ample mask into the sparse bitmap pass
            _RUNTIME["ample_set"] = True
        elif a == "--unsound-ok":
            # waive the reduction soundness-certificate gates
            # (analysis/soundness.py): an UNCERTIFIED spec or mask
            # runs anyway — the counts carry no soundness guarantee
            _RUNTIME["unsound_ok"] = True
        elif a.startswith("--straggler-factor="):
            val = a.split("=", 1)[1]
            f = float(val)
            if f <= 1:
                raise SystemExit(
                    f"--straggler-factor={val}: must be > 1 (a shard "
                    "flags when its wave work exceeds factor x the "
                    "shard median)"
                )
            _RUNTIME["straggler_factor"] = f
        else:
            rest.append(a)
    return rest


def main(argv: list[str] | None = None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    # reset per invocation: main() is re-entered in-process (tests,
    # embedders) and a previous call's checkpoint/resume flags must
    # not leak into a lane that never asked for them
    _RUNTIME.update(
        checkpoint_every=None, checkpoint_path=None, resume=False,
        resume_any_sha=False, waves_per_sync=None,
        tier_hot_rows=None, degrade_on_fault=False, watchdog=None,
        straggler_factor=None, symmetry=False, ample_set=False,
        unsound_ok=False,
    )
    # resident-service lanes (ROADMAP direction 4, serve.py): the
    # daemon, and the client mode that ships a lane to one
    connect, argv = _pop_connect_flag(argv)
    if argv and argv[0] == "serve":
        from . import serve

        raise SystemExit(serve.daemon_main(argv[1:]))
    if connect is not None:
        from . import serve

        raise SystemExit(serve.client_main(connect, argv))
    # the static-analysis lanes: `analyze soundness [MODEL]` runs the
    # reduction soundness analyzer and writes SOUND_r*.json
    if argv and argv[0] == "analyze":
        from .analysis.soundness import analyze_main

        raise SystemExit(analyze_main(argv[1:]))
    trace_level, argv = _pop_trace_flag(argv)
    metrics_interval, metrics_path, argv = _pop_metrics_flags(argv)
    argv = _pop_runtime_flags(argv)
    if not argv or argv[0] not in _MODELS:
        _usage()
        return
    model, rest = argv[0], argv[1:]
    handler, subs = _MODELS[model]
    if not rest or rest[0] not in subs:
        _usage(model)
        return
    if trace_level is None and metrics_interval is None:
        handler(rest[0], rest[1:])
        return
    if trace_level not in (None, "default", "deep"):
        raise SystemExit(
            f"--trace={trace_level}: unknown level "
            "(use --trace or --trace=deep)"
        )
    from .telemetry import RunTracer, write_artifacts

    # --metrics-interval implies a tracer (the rollup is bridge-
    # derived from telemetry events) but NOT trace artifacts: those
    # stay --trace's call
    tracer = RunTracer(level=trace_level or "default")
    rollup = None
    if metrics_interval is not None:
        from .metrics import Rollup, bridge_events

        def _registry_now(tracer=tracer):
            with tracer._lock:
                events = list(tracer.events)
            return bridge_events(events)

        rollup = Rollup(
            metrics_path or "stateright_tpu.metrics.jsonl",
            metrics_interval, source=_registry_now,
        ).start()
    try:
        with tracer.activate():
            handler(rest[0], rest[1:])
    finally:
        if rollup is not None:
            # the final rollup: even a run shorter than one interval
            # leaves the cumulative totals line
            rollup.stop()
        # A failed/interrupted run's partial trace is the one you
        # need for diagnosis — write whatever was collected.
        if trace_level is not None and tracer.events:
            jsonl, chrome = write_artifacts(tracer)
            print(f"trace: wrote {jsonl} + {chrome}", file=sys.stderr)
