"""The ``Actor`` abstraction: message-driven state machines.

Counterpart of stateright src/actor.rs:108-341. An actor initializes
state in ``on_start`` and reacts to messages (``on_msg``) and timers
(``on_timeout``), reading its state through a copy-on-write handle and
emitting :class:`Command`s through an :class:`Out` buffer. The same
actor code is both model-checked (:mod:`stateright_tpu.actor.model`)
and executed over real UDP (:mod:`stateright_tpu.actor.spawn`).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Generic, Iterable, Optional, Sequence, Tuple, TypeVar

Msg = Any
Timer = Any


class Id(int):
    """An actor identifier (src/actor.rs:108-156).

    In a model it is the actor's index; at runtime it packs an
    IPv4 address + port (``Id.from_addr`` / ``to_addr``) exactly like
    the reference's ``u64`` packing (spawn.rs:10-34).
    """

    def __repr__(self) -> str:
        return f"Id({int(self)})"

    @staticmethod
    def from_addr(ip: str, port: int) -> "Id":
        packed = 0
        for part in ip.split("."):
            packed = (packed << 8) | int(part)
        return Id((packed << 16) | port)

    def to_addr(self) -> Tuple[str, int]:
        port = int(self) & 0xFFFF
        packed = int(self) >> 16
        ip = ".".join(str((packed >> shift) & 0xFF) for shift in (24, 16, 8, 0))
        return ip, port


@dataclass(frozen=True)
class Send:
    """Command: send ``msg`` to ``dst`` (src/actor.rs:159-243)."""

    dst: Id
    msg: Msg


@dataclass(frozen=True)
class SetTimer:
    """Command: arm a named timer. The duration range matters only at
    runtime; model checking abstracts it away (actor/timers.rs:7-44)."""

    timer: Timer
    min_sec: float = 0.0
    max_sec: float = 0.0


@dataclass(frozen=True)
class CancelTimer:
    timer: Timer


Command = Any  # Send | SetTimer | CancelTimer


def model_timeout() -> Tuple[float, float]:
    """Arbitrary timeout range for model checking (model.rs:69-71)."""
    return (0.0, 0.0)


def model_peers(self_ix: int, count: int) -> list[Id]:
    """All other actor ids in a ``count``-actor system (model.rs:75-80)."""
    return [Id(j) for j in range(count) if j != self_ix]


def majority(count: int) -> int:
    """Minimum majority size (src/actor.rs:552-554)."""
    return count // 2 + 1


class Out:
    """Buffer of commands an actor emits while handling an event
    (src/actor.rs:159-243)."""

    __slots__ = ("commands",)

    def __init__(self):
        self.commands: list[Command] = []

    def send(self, dst: Id, msg: Msg) -> None:
        self.commands.append(Send(Id(dst), msg))

    def broadcast(self, dsts: Iterable[Id], msg: Msg) -> None:
        """Send to every id in ``dsts`` (src/actor.rs:208-215)."""
        for dst in dsts:
            self.send(dst, msg)

    def set_timer(self, timer: Timer, duration_range: Tuple[float, float]) -> None:
        lo, hi = duration_range
        self.commands.append(SetTimer(timer, lo, hi))

    def cancel_timer(self, timer: Timer) -> None:
        self.commands.append(CancelTimer(timer))

    def append(self, other: "Out") -> None:
        self.commands.extend(other.commands)
        other.commands.clear()

    def __iter__(self):
        return iter(self.commands)

    def __len__(self) -> int:
        return len(self.commands)


class Cow:
    """Copy-on-write state handle (Rust ``Cow<State>`` analog,
    src/actor.rs:247-264).

    Handlers read ``state.value`` and replace it with ``state.set(new)``.
    Whether ``set`` was called is the "owned" bit used for no-op
    detection — a handler that neither sets state nor emits commands
    produces no transition, pruning the state space (model.rs:317-319).
    """

    __slots__ = ("value", "owned")

    def __init__(self, value: Any):
        self.value = value
        self.owned = False

    def set(self, new_value: Any) -> None:
        self.value = new_value
        self.owned = True


def is_no_op(state: Cow, out: Out) -> bool:
    """True iff the handler neither updated state nor emitted commands
    (src/actor.rs:247-249)."""
    return not state.owned and not out.commands


def is_no_op_with_timer(state: Cow, out: Out, timer: Timer) -> bool:
    """True iff the handler only re-armed the same timer
    (src/actor.rs:254-264)."""
    if state.owned:
        return False
    return len(out.commands) == 1 and (
        isinstance(out.commands[0], SetTimer) and out.commands[0].timer == timer
    )


class Actor:
    """A message-driven state machine (src/actor.rs:270-341)."""

    def on_start(self, id: Id, out: Out) -> Any:
        """Return the initial state, optionally emitting commands."""
        raise NotImplementedError

    def on_msg(self, id: Id, state: Cow, src: Id, msg: Msg, out: Out) -> None:
        pass

    def on_timeout(self, id: Id, state: Cow, timer: Timer, out: Out) -> None:
        pass

    def name(self) -> str:
        return type(self).__name__
