"""Write-once-register protocol adapters.

Counterpart of stateright src/actor/write_once_register.rs:16-331: the
register client/server protocol extended with ``PutFail`` (a rejected
write — write-once semantics), history hooks feeding a
``ConsistencyTester`` over :class:`~stateright_tpu.semantics.WORegister`,
and the model-checking client that puts then gets, treating PutFail
like PutOk for sequencing (write_once_register.rs:246-265).

``Put``/``Get``/``PutOk``/``GetOk``/``Internal`` are shared with the
plain register protocol (actor/register.py); only ``PutFail`` is new.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ..semantics.register import ReadOk, ReadOp, WriteOk, WriteOp
from ..semantics.write_once_register import WriteFail
from .base import Actor, Cow, Id, Out
from .network import Envelope
from .register import Get, GetOk, Internal, Put, PutOk, RegisterClientState

__all__ = [
    "Put",
    "Get",
    "PutOk",
    "PutFail",
    "GetOk",
    "Internal",
    "record_invocations",
    "record_returns",
    "WORegisterClient",
    "WORegisterServer",
]


@dataclass(frozen=True)
class PutFail:
    """An unsuccessful Put (write_once_register.rs:27-28)."""

    req_id: int


def record_invocations(cfg: Any, history, env: Envelope):
    """``record_msg_out`` hook (write_once_register.rs:39-62)."""
    if isinstance(env.msg, Get):
        return history.on_invoke(env.src, ReadOp())
    if isinstance(env.msg, Put):
        return history.on_invoke(env.src, WriteOp(env.msg.value))
    return None


def record_returns(cfg: Any, history, env: Envelope):
    """``record_msg_in`` hook, including WriteFail for PutFail
    (write_once_register.rs:68-97)."""
    if isinstance(env.msg, GetOk):
        return history.on_return(env.dst, ReadOk(env.msg.value))
    if isinstance(env.msg, PutOk):
        return history.on_return(env.dst, WriteOk())
    if isinstance(env.msg, PutFail):
        return history.on_return(env.dst, WriteFail())
    return None


class WORegisterClient(Actor):
    """Puts ``put_count`` values then gets; a rejected put (PutFail)
    advances the sequence just like a successful one
    (write_once_register.rs:100-273)."""

    def __init__(self, put_count: int, server_count: int):
        self.put_count = put_count
        self.server_count = server_count

    def name(self) -> str:
        return "Client"

    def on_start(self, id: Id, out: Out) -> RegisterClientState:
        index = int(id)
        if index < self.server_count:
            raise ValueError(
                "WO-register clients must be added to the model after servers"
            )
        if self.put_count == 0:
            return RegisterClientState(awaiting=None, op_count=0)
        req_id = index
        value = chr(ord("A") + index - self.server_count)
        out.send(Id(index % self.server_count), Put(req_id, value))
        return RegisterClientState(awaiting=req_id, op_count=1)

    def on_msg(self, id: Id, state: Cow, src: Id, msg: Any, out: Out) -> None:
        client = state.value
        if client.awaiting is None:
            return
        index = int(id)
        if (
            isinstance(msg, (PutOk, PutFail))
            and msg.req_id == client.awaiting
        ):
            req_id = (client.op_count + 1) * index
            if client.op_count < self.put_count:
                value = chr(ord("Z") - (index - self.server_count))
                out.send(
                    Id((index + client.op_count) % self.server_count),
                    Put(req_id, value),
                )
            else:
                out.send(
                    Id((index + client.op_count) % self.server_count),
                    Get(req_id),
                )
            state.set(
                RegisterClientState(
                    awaiting=req_id, op_count=client.op_count + 1
                )
            )
        elif isinstance(msg, GetOk) and msg.req_id == client.awaiting:
            state.set(
                RegisterClientState(
                    awaiting=None, op_count=client.op_count + 1
                )
            )


class WORegisterServer(Actor):
    """Wraps a server actor, delegating events
    (write_once_register.rs:275-296 server arm)."""

    def __init__(self, inner: Actor):
        self.inner = inner

    def name(self) -> str:
        return self.inner.name() or "Server"

    def on_start(self, id: Id, out: Out):
        return self.inner.on_start(id, out)

    def on_msg(self, id: Id, state: Cow, src: Id, msg: Any, out: Out) -> None:
        self.inner.on_msg(id, state, src, msg, out)

    def on_timeout(self, id: Id, state: Cow, timer: Any, out: Out) -> None:
        self.inner.on_timeout(id, state, timer, out)
