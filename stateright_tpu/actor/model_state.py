"""The system state of a checked actor model.

Counterpart of stateright src/actor/model_state.rs:12-18: per-actor
states, the network value, per-actor timer sets, crash flags, and the
auxiliary history. Immutable (functional updates via ``replace``);
unchanged actor states are shared by reference across system states,
matching the reference's ``Vec<Arc<A::State>>`` sharing.

The symmetry-reduction ``representative`` (model_state.rs:115-132)
lives in :mod:`stateright_tpu.symmetry` and is attached here.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Tuple

from .network import Network


@dataclass(frozen=True)
class ActorModelState:
    actor_states: Tuple[Any, ...]
    network: Network
    timers_set: Tuple[frozenset, ...]
    crashed: Tuple[bool, ...]
    history: Any = ()

    def with_actor_state(self, index: int, state: Any) -> "ActorModelState":
        states = (
            self.actor_states[:index] + (state,) + self.actor_states[index + 1:]
        )
        return replace(self, actor_states=states)

    def with_timers(self, index: int, timers: frozenset) -> "ActorModelState":
        ts = self.timers_set[:index] + (timers,) + self.timers_set[index + 1:]
        return replace(self, timers_set=ts)

    def representative(self) -> "ActorModelState":
        """Canonical member of this state's symmetry class
        (model_state.rs:115-132). Requires the symmetry module."""
        from ..symmetry import actor_state_representative

        return actor_state_representative(self)
